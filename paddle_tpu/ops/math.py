"""Elementwise math + reductions (paddle/tensor/math.py parity, UNVERIFIED)."""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..framework.core import (Tensor, apply, to_jax_dtype, tape_alias, tape_rebind)
from .common import as_tensor, unary, binary

__all__ = [
    # binary
    "add", "subtract", "multiply", "divide", "floor_divide", "mod",
    "remainder", "pow", "float_power", "maximum", "minimum", "fmax", "fmin", "atan2",
    "logaddexp", "heaviside", "copysign", "nextafter", "ldexp", "hypot",
    "gcd", "lcm", "inner", "outer", "kron",
    # unary
    "abs", "acos", "acosh", "asin", "asinh", "atan", "atanh", "ceil", "cos",
    "cosh", "deg2rad", "digamma", "erf", "erfinv", "exp", "expm1", "floor",
    "frac", "lgamma", "log", "log10", "log1p", "log2", "logit", "neg",
    "rad2deg", "reciprocal", "round", "rsqrt", "sigmoid", "sign", "sgn",
    "sin", "sinh", "sqrt", "square", "tan", "tanh", "trunc", "angle",
    "conj", "real", "imag", "i0", "i0e", "i1", "i1e", "polygamma",
    "isfinite", "isinf", "isnan", "nan_to_num",
    # reductions
    "sum", "mean", "max", "min", "prod", "amax", "amin", "all", "any",
    "logsumexp", "nansum", "nanmean", "count_nonzero",
    # scans
    "cumsum", "cumprod", "cummax", "cummin", "logcumsumexp",
    # other
    "clip", "lerp", "addmm", "trace", "diagonal", "multiplex",
    "scale", "stanh", "softplus", "increment", "isclose", "allclose",
    "floor_mod", "divide_no_nan",
]

# ---- binary ---------------------------------------------------------------

add = binary(jnp.add, "add")
subtract = binary(jnp.subtract, "subtract")
multiply = binary(jnp.multiply, "multiply")
divide = binary(jnp.divide, "divide")
floor_divide = binary(jnp.floor_divide, "floor_divide")
mod = binary(jnp.mod, "mod")
remainder = mod
floor_mod = mod
maximum = binary(jnp.maximum, "maximum")
minimum = binary(jnp.minimum, "minimum")
fmax = binary(jnp.fmax, "fmax")
fmin = binary(jnp.fmin, "fmin")
atan2 = binary(jnp.arctan2, "atan2")
logaddexp = binary(jnp.logaddexp, "logaddexp")
heaviside = binary(jnp.heaviside, "heaviside")
copysign = binary(jnp.copysign, "copysign")
nextafter = binary(jnp.nextafter, "nextafter")
hypot = binary(jnp.hypot, "hypot")
gcd = binary(jnp.gcd, "gcd")
lcm = binary(jnp.lcm, "lcm")


def float_power(x, y, name=None):
    """x ** y computed in float64-free style: promote to the widest
    float of the inputs (paddle float_power promotes to double; on TPU
    we stay at f32 unless x64 is enabled)."""
    def fn(a, b):
        tgt = jnp.promote_types(jnp.result_type(a, b), jnp.float32)
        return jnp.power(a.astype(tgt), jnp.asarray(b).astype(tgt))
    return binary(fn, "float_power")(x, y)


def pow(x, y, name=None):
    return binary(jnp.power, "pow")(x, y)


def ldexp(x, y, name=None):
    return apply(lambda a, b: a * (2.0 ** b.astype(jnp.float32)),
                 as_tensor(x), as_tensor(y), name="ldexp")


def divide_no_nan(x, y, name=None):
    return apply(lambda a, b: jnp.where(b == 0, jnp.zeros_like(a + b), a / b),
                 as_tensor(x), as_tensor(y), name="divide_no_nan")


def inner(x, y, name=None):
    return apply(jnp.inner, as_tensor(x), as_tensor(y), name="inner")


def outer(x, y, name=None):
    return apply(lambda a, b: jnp.outer(a, b), as_tensor(x), as_tensor(y),
                 name="outer")


def kron(x, y, name=None):
    return apply(jnp.kron, as_tensor(x), as_tensor(y), name="kron")


# ---- unary ----------------------------------------------------------------

abs = unary(jnp.abs, "abs")
acos = unary(jnp.arccos, "acos")
acosh = unary(jnp.arccosh, "acosh")
asin = unary(jnp.arcsin, "asin")
asinh = unary(jnp.arcsinh, "asinh")
atan = unary(jnp.arctan, "atan")
atanh = unary(jnp.arctanh, "atanh")
ceil = unary(jnp.ceil, "ceil")
cos = unary(jnp.cos, "cos")
cosh = unary(jnp.cosh, "cosh")
deg2rad = unary(jnp.deg2rad, "deg2rad")
digamma = unary(jax.scipy.special.digamma, "digamma")
erf = unary(jax.scipy.special.erf, "erf")
erfinv = unary(jax.scipy.special.erfinv, "erfinv")
exp = unary(jnp.exp, "exp")
expm1 = unary(jnp.expm1, "expm1")
floor = unary(jnp.floor, "floor")
frac = unary(lambda a: a - jnp.trunc(a), "frac")
lgamma = unary(jax.scipy.special.gammaln, "lgamma")
log = unary(jnp.log, "log")
log10 = unary(jnp.log10, "log10")
log1p = unary(jnp.log1p, "log1p")
log2 = unary(jnp.log2, "log2")
neg = unary(jnp.negative, "neg")
rad2deg = unary(jnp.rad2deg, "rad2deg")
reciprocal = unary(jnp.reciprocal, "reciprocal")
round = unary(jnp.round, "round")
rsqrt = unary(jax.lax.rsqrt, "rsqrt")
sigmoid = unary(jax.nn.sigmoid, "sigmoid")
sign = unary(jnp.sign, "sign")
sgn = sign
sin = unary(jnp.sin, "sin")
sinh = unary(jnp.sinh, "sinh")
sqrt = unary(jnp.sqrt, "sqrt")
square = unary(jnp.square, "square")
tan = unary(jnp.tan, "tan")
tanh = unary(jnp.tanh, "tanh")
trunc = unary(jnp.trunc, "trunc")
angle = unary(jnp.angle, "angle")
conj = unary(jnp.conj, "conj")
real = unary(jnp.real, "real")
imag = unary(jnp.imag, "imag")
i0 = unary(jax.scipy.special.i0, "i0")
i0e = unary(jax.scipy.special.i0e, "i0e")
i1 = unary(jax.scipy.special.i1, "i1")
i1e = unary(jax.scipy.special.i1e, "i1e")
isfinite = unary(jnp.isfinite, "isfinite")
isinf = unary(jnp.isinf, "isinf")
isnan = unary(jnp.isnan, "isnan")


def logit(x, eps=None, name=None):
    def fn(a):
        b = a if eps is None else jnp.clip(a, eps, 1.0 - eps)
        return jnp.log(b / (1.0 - b))
    return apply(fn, as_tensor(x), name="logit")


def polygamma(x, n, name=None):
    return apply(lambda a: jax.scipy.special.polygamma(n, a), as_tensor(x),
                 name="polygamma")


def nan_to_num(x, nan=0.0, posinf=None, neginf=None, name=None):
    return apply(lambda a: jnp.nan_to_num(a, nan=nan, posinf=posinf,
                                          neginf=neginf),
                 as_tensor(x), name="nan_to_num")


def stanh(x, scale_a=0.67, scale_b=1.7159, name=None):
    return apply(lambda a: scale_b * jnp.tanh(scale_a * a), as_tensor(x),
                 name="stanh")


def softplus(x, beta=1, threshold=20, name=None):
    def fn(a):
        bx = beta * a
        return jnp.where(bx > threshold, a, jnp.logaddexp(bx, 0.0) / beta)
    return apply(fn, as_tensor(x), name="softplus")


# ---- reductions -----------------------------------------------------------

def _axes(axis):
    if axis is None:
        return None
    if isinstance(axis, Tensor):
        axis = axis.tolist()
    if isinstance(axis, (list, tuple)):
        return tuple(int(a) for a in axis)
    return int(axis)


def _reduce(jfn, name):
    def op(x, axis=None, keepdim=False, name=None, dtype=None):
        x = as_tensor(x)

        def fn(a):
            out = jfn(a, axis=_axes(axis), keepdims=keepdim)
            if dtype is not None:
                out = out.astype(to_jax_dtype(dtype))
            return out
        return apply(fn, x, name=name)
    op.__name__ = name
    return op


sum = _reduce(jnp.sum, "sum")
mean = _reduce(jnp.mean, "mean")
prod = _reduce(jnp.prod, "prod")
amax = _reduce(jnp.max, "amax")
amin = _reduce(jnp.min, "amin")
nansum = _reduce(jnp.nansum, "nansum")
nanmean = _reduce(jnp.nanmean, "nanmean")


def max(x, axis=None, keepdim=False, name=None):
    return _reduce(jnp.max, "max")(x, axis, keepdim)


def min(x, axis=None, keepdim=False, name=None):
    return _reduce(jnp.min, "min")(x, axis, keepdim)


def all(x, axis=None, keepdim=False, name=None):
    x = as_tensor(x)
    return Tensor(jnp.all(x._data, axis=_axes(axis), keepdims=keepdim))


def any(x, axis=None, keepdim=False, name=None):
    x = as_tensor(x)
    return Tensor(jnp.any(x._data, axis=_axes(axis), keepdims=keepdim))


def logsumexp(x, axis=None, keepdim=False, name=None):
    x = as_tensor(x)
    return apply(lambda a: jax.scipy.special.logsumexp(
        a, axis=_axes(axis), keepdims=keepdim), x, name="logsumexp")


def count_nonzero(x, axis=None, keepdim=False, name=None):
    x = as_tensor(x)
    return Tensor(jnp.count_nonzero(x._data, axis=_axes(axis),
                                    keepdims=keepdim))


# ---- scans ----------------------------------------------------------------

def cumsum(x, axis=None, dtype=None, name=None):
    x = as_tensor(x)

    def fn(a):
        if axis is None:
            a = a.reshape(-1)
            return jnp.cumsum(a, dtype=to_jax_dtype(dtype))
        return jnp.cumsum(a, axis=int(axis), dtype=to_jax_dtype(dtype))
    return apply(fn, x, name="cumsum")


def cumprod(x, dim=None, dtype=None, name=None):
    x = as_tensor(x)

    def fn(a):
        if dim is None:
            a = a.reshape(-1)
            return jnp.cumprod(a, dtype=to_jax_dtype(dtype))
        return jnp.cumprod(a, axis=int(dim), dtype=to_jax_dtype(dtype))
    return apply(fn, x, name="cumprod")


def cummax(x, axis=None, dtype="int64", name=None):
    x = as_tensor(x)
    ax = 0 if axis is None else int(axis)
    data = x._data.reshape(-1) if axis is None else x._data
    vals = jax.lax.associative_scan(jnp.maximum, data, axis=ax)
    idx_src = jnp.arange(data.shape[ax]).reshape(
        [-1 if i == ax % data.ndim else 1 for i in range(data.ndim)])
    idx_src = jnp.broadcast_to(idx_src, data.shape)

    def take_pair(a, b):
        av, ai = a
        bv, bi = b
        keep = av >= bv
        return jnp.where(keep, av, bv), jnp.where(keep, ai, bi)
    _, idx = jax.lax.associative_scan(take_pair, (data, idx_src), axis=ax)
    return Tensor(vals), Tensor(idx.astype(to_jax_dtype(dtype)))


def cummin(x, axis=None, dtype="int64", name=None):
    x = as_tensor(x)
    neg_vals, idx = cummax(Tensor(-x._data), axis=axis, dtype=dtype)
    return Tensor(-neg_vals._data), idx


def logcumsumexp(x, axis=None, name=None):
    x = as_tensor(x)

    def fn(a):
        if axis is None:
            b, ax = a.reshape(-1), 0
        else:
            b, ax = a, int(axis)
        mx = jnp.max(b, axis=ax, keepdims=True)
        return jnp.log(jnp.cumsum(jnp.exp(b - mx), axis=ax)) + mx
    return apply(fn, x, name="logcumsumexp")


# ---- other ----------------------------------------------------------------

def clip(x, min=None, max=None, name=None):
    x = as_tensor(x)
    lo = min.item() if isinstance(min, Tensor) and min.ndim == 0 else min
    hi = max.item() if isinstance(max, Tensor) and max.ndim == 0 else max
    if isinstance(lo, Tensor) or isinstance(hi, Tensor):
        args = [x]
        def fn(a, *mm):
            i = 0
            l, h = lo, hi
            if isinstance(lo, Tensor):
                l = mm[i]; i += 1
            if isinstance(hi, Tensor):
                h = mm[i]
            return jnp.clip(a, l, h)
        extra = [t for t in (lo, hi) if isinstance(t, Tensor)]
        return apply(fn, x, *extra, name="clip")
    return apply(lambda a: jnp.clip(a, lo, hi), x, name="clip")


def lerp(x, y, weight, name=None):
    if isinstance(weight, Tensor):
        return apply(lambda a, b, w: a + w * (b - a), as_tensor(x),
                     as_tensor(y), weight, name="lerp")
    return apply(lambda a, b: a + weight * (b - a), as_tensor(x),
                 as_tensor(y), name="lerp")


def addmm(input, x, y, beta=1.0, alpha=1.0, name=None):
    return apply(lambda i, a, b: beta * i + alpha * (a @ b),
                 as_tensor(input), as_tensor(x), as_tensor(y), name="addmm")


def trace(x, offset=0, axis1=0, axis2=1, name=None):
    return apply(lambda a: jnp.trace(a, offset=offset, axis1=axis1,
                                     axis2=axis2), as_tensor(x), name="trace")


def diagonal(x, offset=0, axis1=0, axis2=1, name=None):
    return apply(lambda a: jnp.diagonal(a, offset=offset, axis1=axis1,
                                        axis2=axis2),
                 as_tensor(x), name="diagonal")


def multiplex(inputs, index, name=None):
    ins = [as_tensor(i) for i in inputs]
    idx = as_tensor(index)

    def fn(ix, *xs):
        stacked = jnp.stack(xs, axis=0)
        sel = ix.reshape(-1).astype(jnp.int32)
        return jnp.take_along_axis(
            stacked, sel[None, :].reshape((1, -1) + (1,) * (stacked.ndim - 2)),
            axis=0)[0]
    return apply(fn, idx, *ins, name="multiplex")


def scale(x, scale=1.0, bias=0.0, bias_after_scale=True, act=None, name=None):
    x = as_tensor(x)
    if isinstance(scale, Tensor):
        def fn(a, s):
            return a * s + bias if bias_after_scale else (a + bias) * s
        out = apply(fn, x, scale, name="scale")
    else:
        def fn(a):
            return a * scale + bias if bias_after_scale else (a + bias) * scale
        out = apply(fn, x, name="scale")
    if act is not None:
        from ..nn import functional as F
        out = getattr(F, act)(out)
    return out


def increment(x, value=1.0, name=None):
    if isinstance(x, Tensor):
        out = apply(lambda a: a + value, tape_alias(x), name="increment")
        return tape_rebind(x, out)
    return apply(lambda a: a + value, as_tensor(x), name="increment")


def isclose(x, y, rtol=1e-05, atol=1e-08, equal_nan=False, name=None):
    return Tensor(jnp.isclose(as_tensor(x)._data, as_tensor(y)._data,
                              rtol=rtol, atol=atol, equal_nan=equal_nan))


def allclose(x, y, rtol=1e-05, atol=1e-08, equal_nan=False, name=None):
    return Tensor(jnp.allclose(as_tensor(x)._data, as_tensor(y)._data,
                               rtol=rtol, atol=atol, equal_nan=equal_nan))


# ---- long-tail math (round-2 breadth) -------------------------------------

import numpy as _np

signbit = unary(jnp.signbit, "signbit")
isposinf = unary(jnp.isposinf, "isposinf")
isneginf = unary(jnp.isneginf, "isneginf")
sinc = unary(jnp.sinc, "sinc")
positive = unary(lambda a: a, "positive")
negative = unary(jnp.negative, "negative")
gammaln = unary(jax.scipy.special.gammaln, "gammaln")
gammainc = binary(jax.scipy.special.gammainc, "gammainc")
gammaincc = binary(jax.scipy.special.gammaincc, "gammaincc")
bitwise_invert = unary(jnp.invert, "bitwise_invert")


def isreal(x, name=None):
    x = as_tensor(x)
    return apply(lambda a: (jnp.imag(a) == 0 if jnp.iscomplexobj(a)
                            else jnp.ones(a.shape, bool)), x, name="isreal")


def isin(x, test_x, assume_unique=False, invert=False, name=None):
    return apply(lambda a, t: jnp.isin(a, t, invert=invert),
                 as_tensor(x), as_tensor(test_x), name="isin")


def frexp(x, name=None):
    x = as_tensor(x)
    m, e = apply(lambda a: tuple(jnp.frexp(a)), x, n_outputs=2,
                 name="frexp", differentiable=False)
    return m, e


def multigammaln(x, p, name=None):
    x = as_tensor(x)
    return apply(lambda a: jax.scipy.special.multigammaln(a, int(p)), x,
                 name="multigammaln")


def trapezoid(y, x=None, dx=None, axis=-1, name=None):
    y = as_tensor(y)
    if x is not None:
        return apply(lambda yy, xx: jnp.trapezoid(yy, xx, axis=axis),
                     y, as_tensor(x), name="trapezoid")
    return apply(lambda yy: jnp.trapezoid(
        yy, dx=1.0 if dx is None else float(dx), axis=axis),
        y, name="trapezoid")


def cumulative_trapezoid(y, x=None, dx=None, axis=-1, name=None):
    y = as_tensor(y)
    ax = int(axis)

    def pair_sum(yy, spacing):
        y0 = jax.lax.slice_in_dim(yy, 0, yy.shape[ax] - 1, axis=ax)
        y1 = jax.lax.slice_in_dim(yy, 1, yy.shape[ax], axis=ax)
        return jnp.cumsum((y0 + y1) * 0.5 * spacing, axis=ax)

    if x is not None:
        def fn(yy, xx):
            x0 = jax.lax.slice_in_dim(xx, 0, xx.shape[ax if xx.ndim > 1
                                                      else 0] - 1,
                                      axis=ax if xx.ndim > 1 else 0)
            x1 = jax.lax.slice_in_dim(xx, 1, xx.shape[ax if xx.ndim > 1
                                                      else 0],
                                      axis=ax if xx.ndim > 1 else 0)
            d = x1 - x0
            if xx.ndim == 1 and yy.ndim > 1:
                shape = [1] * yy.ndim
                shape[ax] = -1
                d = d.reshape(shape)
            return pair_sum(yy, d)
        return apply(fn, y, as_tensor(x), name="cumulative_trapezoid")
    return apply(lambda yy: pair_sum(yy, 1.0 if dx is None else float(dx)),
                 y, name="cumulative_trapezoid")


def renorm(x, p, axis, max_norm, name=None):
    """Rescale sub-tensors along ``axis`` whose p-norm exceeds max_norm
    (paddle.renorm)."""
    x = as_tensor(x)

    def fn(a):
        moved = jnp.moveaxis(a, int(axis), 0)
        flat = moved.reshape(moved.shape[0], -1)
        norms = jnp.sum(jnp.abs(flat) ** p, axis=1) ** (1.0 / p)
        scale = jnp.where(norms > max_norm,
                          max_norm / (norms + 1e-7), 1.0)
        out = flat * scale[:, None]
        return jnp.moveaxis(out.reshape(moved.shape), 0, int(axis))
    return apply(fn, x, name="renorm")


def renorm_(x, p, axis, max_norm, name=None):
    return tape_rebind(x, renorm(tape_alias(x), p, axis, max_norm))


def reduce_as(x, target, name=None):
    """Sum-reduce x down to target's shape (paddle.reduce_as)."""
    x, target = as_tensor(x), as_tensor(target)
    tshape = tuple(target.shape)

    def fn(a):
        extra = a.ndim - len(tshape)
        if extra > 0:
            a = jnp.sum(a, axis=tuple(range(extra)))
        keep = tuple(i for i, (s, t) in enumerate(zip(a.shape, tshape))
                     if s != t)
        if keep:
            a = jnp.sum(a, axis=keep, keepdims=True)
        return a
    return apply(fn, x, name="reduce_as")


# ---- the in-place op family (paddle `op_`) --------------------------------

def _make_inplace(op_fn, op_name):
    def op_(x, *args, **kwargs):
        kwargs.pop("name", None)
        return tape_rebind(x, op_fn(tape_alias(x), *args, **kwargs))
    op_.__name__ = op_name
    op_.__doc__ = f"In-place variant of ``{op_name[:-1]}`` (paddle parity)."
    return op_


_INPLACE_UNARY = [
    "exp", "sqrt", "rsqrt", "reciprocal", "round", "ceil", "floor",
    "trunc", "abs", "sin", "cos", "tan", "tanh", "asin", "acos", "atan",
    "sinh", "cosh", "asinh", "acosh", "atanh", "sigmoid", "log", "log2",
    "log10", "log1p", "erf", "expm1", "neg", "square", "digamma",
    "lgamma", "i0", "frac", "logit", "nan_to_num", "bitwise_not",
    "bitwise_invert", "gammaln",
]
_INPLACE_BINARY = [
    "add", "subtract", "multiply", "divide", "remainder", "floor_divide",
    "mod", "pow", "lerp", "copysign", "hypot", "ldexp", "nextafter",
    "heaviside", "bitwise_and", "bitwise_or", "bitwise_xor",
    "logical_and", "logical_or", "logical_xor", "gammainc", "gammaincc",
    "fmax", "fmin", "maximum", "minimum", "atan2",
]
_INPLACE_OTHER = ["clip", "scale", "lcm", "gcd"]

_g = globals()
for _n in _INPLACE_UNARY + _INPLACE_BINARY + _INPLACE_OTHER:
    _fn = _g.get(_n)
    if _fn is None:
        from . import logic as _logic_mod
        _fn = getattr(_logic_mod, _n, None)
    if _fn is None:
        continue
    _g[_n + "_"] = _make_inplace(_fn, _n + "_")
    __all__.append(_n + "_")

__all__ += [
    "signbit", "isposinf", "isneginf", "isreal", "isin", "sinc", "frexp",
    "positive", "negative", "gammaln", "gammainc", "gammaincc",
    "multigammaln", "bitwise_invert", "trapezoid", "cumulative_trapezoid",
    "renorm", "renorm_", "reduce_as",
]


def logaddexp2(x, y, name=None):
    return apply(jnp.logaddexp2, as_tensor(x), as_tensor(y),
                 name="logaddexp2")


def add_n(inputs, name=None):
    """Elementwise sum of a tensor list (paddle.add_n)."""
    if isinstance(inputs, Tensor):
        return inputs
    import functools
    ts = [as_tensor(t) for t in inputs]
    return apply(lambda *arrs: functools.reduce(jnp.add, arrs), *ts,
                 name="add_n")


def rank(input, name=None):
    """Runtime rank as a 0-D int32 tensor (paddle.rank)."""
    from .creation import to_tensor
    return to_tensor(int(as_tensor(input).ndim), dtype="int32")


__all__ += ["logaddexp2", "add_n", "rank"]

# torch-convention incomplete gamma pair (paddle 2.6 added these
# following torch.igamma/igammac): igamma = regularized LOWER P(a, x),
# igammac = regularized UPPER Q(a, x), first argument is the shape a.
igamma = binary(jax.scipy.special.gammainc, "igamma")
igammac = binary(jax.scipy.special.gammaincc, "igammac")


igamma_ = _make_inplace(igamma, "igamma_")
igammac_ = _make_inplace(igammac, "igammac_")

__all__ += ["igamma", "igammac", "igamma_", "igammac_"]
