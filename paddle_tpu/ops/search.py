"""Search/sort ops (paddle/tensor/search.py parity, UNVERIFIED)."""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..framework.core import (Tensor, apply, to_jax_dtype, tape_alias,
                              tape_rebind)
from .common import as_tensor

__all__ = [
    "argmax", "argmin", "argsort", "sort", "searchsorted", "topk", "where", "where_",
    "nonzero", "kthvalue", "mode", "index_sample", "masked_select", "bucketize",
]


def argmax(x, axis=None, keepdim=False, dtype="int64", name=None):
    x = as_tensor(x)
    d = jnp.argmax(x._data if axis is not None else x._data.reshape(-1),
                   axis=axis)
    if keepdim and axis is not None:
        d = jnp.expand_dims(d, axis)
    return Tensor(d.astype(to_jax_dtype(dtype)))


def argmin(x, axis=None, keepdim=False, dtype="int64", name=None):
    x = as_tensor(x)
    d = jnp.argmin(x._data if axis is not None else x._data.reshape(-1),
                   axis=axis)
    if keepdim and axis is not None:
        d = jnp.expand_dims(d, axis)
    return Tensor(d.astype(to_jax_dtype(dtype)))


def argsort(x, axis=-1, descending=False, stable=False, name=None):
    x = as_tensor(x)
    d = jnp.argsort(x._data, axis=axis, stable=True,
                    descending=descending)
    return Tensor(d.astype(jnp.int64))


def sort(x, axis=-1, descending=False, stable=False, name=None):
    x = as_tensor(x)

    def fn(a):
        s = jnp.sort(a, axis=axis, stable=True, descending=descending)
        return s
    return apply(fn, x, name="sort")


def searchsorted(sorted_sequence, values, out_int32=False, right=False,
                 name=None):
    ss, v = as_tensor(sorted_sequence), as_tensor(values)
    side = "right" if right else "left"

    def fn(s, x):
        if s.ndim == 1:
            return jnp.searchsorted(s, x, side=side)
        flat_fn = lambda srow, xrow: jnp.searchsorted(srow, xrow, side=side)
        for _ in range(s.ndim - 1):
            flat_fn = jax.vmap(flat_fn)
        return flat_fn(s, x)
    out = fn(ss._data, v._data)
    return Tensor(out.astype(jnp.int32 if out_int32 else jnp.int64))


def bucketize(x, sorted_sequence, out_int32=False, right=False, name=None):
    return searchsorted(sorted_sequence, x, out_int32=out_int32, right=right)


def topk(x, k, axis=None, largest=True, sorted=True, name=None):
    x = as_tensor(x)
    if isinstance(k, Tensor):
        k = int(k.item())
    ax = -1 if axis is None else int(axis)

    def fn(a):
        b = jnp.moveaxis(a, ax, -1)
        vals, idx = jax.lax.top_k(b if largest else -b, k)
        if not largest:
            vals = -vals
        return jnp.moveaxis(vals, -1, ax), jnp.moveaxis(idx, -1, ax)

    vals, idx = apply(fn, x, n_outputs=2, name="topk")
    return vals, Tensor(idx._data.astype(jnp.int64))


def where(condition, x=None, y=None, name=None):
    condition = as_tensor(condition)
    if x is None and y is None:
        return nonzero(condition, as_tuple=True)
    xt = x if isinstance(x, Tensor) else x
    yt = y if isinstance(y, Tensor) else y
    args = [t for t in (xt, yt) if isinstance(t, Tensor)]

    def fn(c, *ts):
        i = 0
        xx, yy = xt, yt
        if isinstance(xt, Tensor):
            xx = ts[i]; i += 1
        if isinstance(yt, Tensor):
            yy = ts[i]
        return jnp.where(c, xx, yy)
    return apply(fn, condition, *args, name="where")


def where_(condition, x, y=None, name=None):
    """Inplace ``where``: writes the selection back into ``x`` (the
    paddle inplace-API convention) and returns it. Tape-rebinding, not
    set_data: gradients keep flowing through the in-place result."""
    return tape_rebind(x, where(condition, tape_alias(x), y))


def nonzero(x, as_tuple=False, name=None):
    x = as_tensor(x)
    idx = np.nonzero(np.asarray(x._data))
    if as_tuple:
        return tuple(Tensor(jnp.asarray(i[:, None].astype(np.int64)))
                     for i in idx)
    return Tensor(jnp.asarray(np.stack(idx, axis=1).astype(np.int64)))


def kthvalue(x, k, axis=-1, keepdim=False, name=None):
    x = as_tensor(x)

    def fn(a):
        s = jnp.sort(a, axis=axis)
        i = jnp.argsort(a, axis=axis, stable=True)
        vals = jnp.take(s, k - 1, axis=axis)
        idxs = jnp.take(i, k - 1, axis=axis)
        if keepdim:
            vals = jnp.expand_dims(vals, axis)
            idxs = jnp.expand_dims(idxs, axis)
        return vals, idxs
    vals, idx = apply(fn, x, n_outputs=2, name="kthvalue")
    return vals, Tensor(idx._data.astype(jnp.int64))


def mode(x, axis=-1, keepdim=False, name=None):
    x = as_tensor(x)
    data = np.asarray(x._data)
    mv = np.moveaxis(data, axis, -1)
    flat = mv.reshape(-1, mv.shape[-1])
    vals = np.empty(flat.shape[0], dtype=data.dtype)
    idxs = np.empty(flat.shape[0], dtype=np.int64)
    for i, row in enumerate(flat):
        uniq, counts = np.unique(row, return_counts=True)
        # ties resolve to the largest value (np.unique sorts ascending)
        best = uniq[counts == counts.max()][-1]
        vals[i] = best
        idxs[i] = np.where(row == best)[0][-1]
    out_shape = mv.shape[:-1]
    vals = vals.reshape(out_shape)
    idxs = idxs.reshape(out_shape)
    if keepdim:
        vals = np.expand_dims(vals, axis)
        idxs = np.expand_dims(idxs, axis)
    return Tensor(jnp.asarray(vals)), Tensor(jnp.asarray(idxs))


def index_sample(x, index):
    from .manipulation import index_sample as _is
    return _is(x, index)


def masked_select(x, mask, name=None):
    from .manipulation import masked_select as _ms
    return _ms(x, mask)
