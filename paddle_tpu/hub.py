"""paddle.hub — load models/entrypoints from a hubconf.py.

Upstream (``python/paddle/hapi/hub.py``, UNVERIFIED) supports
github/gitee/local sources. This environment has zero egress, so only the
``source='local'`` path is functional; remote sources raise with a clear
message. API shape (list/help/load) is preserved.
"""

from __future__ import annotations

import importlib.util
import os
import sys

MODULE_HUBCONF = "hubconf.py"
_hubconf_cache: dict = {}


def _load_local(repo_dir, force_reload=False):
    repo_dir = os.path.abspath(repo_dir)
    if not force_reload and repo_dir in _hubconf_cache:
        return _hubconf_cache[repo_dir]
    hub_path = os.path.join(repo_dir, MODULE_HUBCONF)
    if not os.path.exists(hub_path):
        raise FileNotFoundError(f"no {MODULE_HUBCONF} in {repo_dir}")
    spec = importlib.util.spec_from_file_location("hubconf", hub_path)
    mod = importlib.util.module_from_spec(spec)
    sys.path.insert(0, repo_dir)
    try:
        spec.loader.exec_module(mod)
    finally:
        sys.path.remove(repo_dir)
    _hubconf_cache[repo_dir] = mod
    return mod


def _entrypoint(mod, model, repo_dir):
    if not hasattr(mod, model):
        raise RuntimeError(f"entrypoint {model!r} not found in {repo_dir}")
    return getattr(mod, model)

def _check_source(source):
    if source != "local":
        raise RuntimeError(
            f"paddle.hub source={source!r} needs network access, which this "
            "environment does not have; clone the repo and use "
            "source='local'.")


def list(repo_dir, source="local", force_reload=False):  # noqa: A001
    _check_source(source)
    mod = _load_local(repo_dir, force_reload)
    return [k for k, v in vars(mod).items()
            if callable(v) and not k.startswith("_")]


def help(repo_dir, model, source="local", force_reload=False):  # noqa: A001
    _check_source(source)
    mod = _load_local(repo_dir, force_reload)
    return _entrypoint(mod, model, repo_dir).__doc__


def load(repo_dir, model, *args, source="local", force_reload=False,
         **kwargs):
    _check_source(source)
    mod = _load_local(repo_dir, force_reload)
    return _entrypoint(mod, model, repo_dir)(*args, **kwargs)


__all__ = ["list", "help", "load"]
