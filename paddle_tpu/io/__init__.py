"""``paddle.io`` — Dataset / DataLoader / samplers
(python/paddle/io/ parity, UNVERIFIED).

TPU-first notes: the DataLoader feeds numpy batches converted to jax
arrays. ``num_workers > 0`` on a map-style dataset spawns real subprocess
workers (spawn context; index queue -> result queue with ordered
reassembly), so Python-heavy transforms run outside the trainer's GIL —
the same process model as the reference's DataLoader. Workers collate to
numpy; tensors materialize on device only in the trainer process (a data
worker must never initialize the TPU client). When the dataset /
collate_fn / worker_init_fn can't be pickled for spawn, the loader warns
and falls back to a prefetch thread pool; IterableDataset streams use a
single background producer thread (the stream itself is sequential)."""

from __future__ import annotations

import collections
import itertools
import math
import multiprocessing
import os
import pickle
import queue
import threading
import time
import warnings
from typing import Iterable, Iterator

import numpy as np

from ..framework.core import Tensor
from ..framework import random as framework_random

__all__ = ["Dataset", "IterableDataset", "TensorDataset", "ComposeDataset",
           "ChainDataset", "ConcatDataset", "Subset", "random_split",
           "Sampler", "SequenceSampler", "RandomSampler", "BatchSampler",
           "WeightedRandomSampler", "DistributedBatchSampler", "DataLoader",
           "get_worker_info", "default_collate_fn", "default_convert_fn",
           "DevicePrefetcher", "device_prefetch"]


class Dataset:
    def __getitem__(self, idx):
        raise NotImplementedError

    def __len__(self):
        raise NotImplementedError


class IterableDataset(Dataset):
    def __iter__(self):
        raise NotImplementedError

    def __getitem__(self, idx):
        raise RuntimeError("IterableDataset does not support indexing")

    def __len__(self):
        raise RuntimeError("IterableDataset has no len()")


class TensorDataset(Dataset):
    def __init__(self, tensors):
        self.tensors = tensors

    def __getitem__(self, idx):
        return tuple(t[idx] for t in self.tensors)

    def __len__(self):
        return self.tensors[0].shape[0]


class ComposeDataset(Dataset):
    def __init__(self, datasets):
        self.datasets = list(datasets)

    def __len__(self):
        return min(len(d) for d in self.datasets)

    def __getitem__(self, idx):
        out = []
        for d in self.datasets:
            item = d[idx]
            if isinstance(item, (list, tuple)):
                out.extend(item)
            else:
                out.append(item)
        return tuple(out)


class ChainDataset(IterableDataset):
    def __init__(self, datasets):
        self.datasets = list(datasets)

    def __iter__(self):
        for d in self.datasets:
            yield from d


class ConcatDataset(Dataset):
    def __init__(self, datasets):
        self.datasets = list(datasets)
        self.cum = np.cumsum([len(d) for d in self.datasets])

    def __len__(self):
        return int(self.cum[-1])

    def __getitem__(self, idx):
        ds_idx = int(np.searchsorted(self.cum, idx, side="right"))
        prev = 0 if ds_idx == 0 else int(self.cum[ds_idx - 1])
        return self.datasets[ds_idx][idx - prev]


class Subset(Dataset):
    def __init__(self, dataset, indices):
        self.dataset = dataset
        self.indices = list(indices)

    def __getitem__(self, idx):
        return self.dataset[self.indices[idx]]

    def __len__(self):
        return len(self.indices)


def random_split(dataset, lengths, generator=None):
    if all(isinstance(l, float) for l in lengths):
        n = len(dataset)
        lengths = [int(math.floor(n * f)) for f in lengths]
        lengths[-1] += n - sum(lengths)
    total = sum(lengths)
    perm = np.random.RandomState(0).permutation(total) if generator is None \
        else np.asarray(generator)
    out, off = [], 0
    for l in lengths:
        out.append(Subset(dataset, perm[off:off + l].tolist()))
        off += l
    return out


# ---- samplers -------------------------------------------------------------

class Sampler:
    def __init__(self, data_source=None):
        self.data_source = data_source

    def __iter__(self):
        raise NotImplementedError


class SequenceSampler(Sampler):
    def __iter__(self):
        return iter(range(len(self.data_source)))

    def __len__(self):
        return len(self.data_source)


class RandomSampler(Sampler):
    def __init__(self, data_source, replacement=False, num_samples=None,
                 generator=None):
        super().__init__(data_source)
        self.replacement = replacement
        self._num_samples = num_samples
        self.generator = generator
        self._epoch_seed = itertools.count()

    @property
    def num_samples(self):
        return self._num_samples or len(self.data_source)

    def __iter__(self):
        n = len(self.data_source)
        rng = np.random.RandomState(next(self._epoch_seed) + 12345)
        if self.replacement:
            return iter(rng.randint(0, n, self.num_samples).tolist())
        return iter(rng.permutation(n)[: self.num_samples].tolist())

    def __len__(self):
        return self.num_samples


class WeightedRandomSampler(Sampler):
    def __init__(self, weights, num_samples, replacement=True):
        self.weights = np.asarray(weights, dtype=np.float64)
        self.num_samples = num_samples
        self.replacement = replacement

    def __iter__(self):
        p = self.weights / self.weights.sum()
        idx = np.random.choice(len(self.weights), self.num_samples,
                               replace=self.replacement, p=p)
        return iter(idx.tolist())

    def __len__(self):
        return self.num_samples


class BatchSampler(Sampler):
    def __init__(self, dataset=None, sampler=None, shuffle=False,
                 batch_size=1, drop_last=False):
        self.batch_size = batch_size
        self.drop_last = drop_last
        if sampler is not None:
            self.sampler = sampler
        elif shuffle:
            self.sampler = RandomSampler(dataset)
        else:
            self.sampler = SequenceSampler(dataset)

    def __iter__(self):
        batch = []
        for idx in self.sampler:
            batch.append(idx)
            if len(batch) == self.batch_size:
                yield batch
                batch = []
        if batch and not self.drop_last:
            yield batch

    def __len__(self):
        n = len(self.sampler)
        if self.drop_last:
            return n // self.batch_size
        return (n + self.batch_size - 1) // self.batch_size


class DistributedBatchSampler(BatchSampler):
    """Shards the dataset across data-parallel ranks
    (fleet DistributedBatchSampler parity)."""

    def __init__(self, dataset, batch_size, num_replicas=None, rank=None,
                 shuffle=False, drop_last=False):
        self.dataset = dataset
        self.batch_size = batch_size
        self.shuffle = shuffle
        self.drop_last = drop_last
        if num_replicas is None or rank is None:
            from ..distributed import get_world_size, get_rank
            num_replicas = num_replicas or get_world_size()
            rank = rank if rank is not None else get_rank()
        self.nranks = num_replicas
        self.local_rank = rank
        self.epoch = 0
        self.num_samples = int(math.ceil(len(dataset) / self.nranks))
        self.total_size = self.num_samples * self.nranks

    def set_epoch(self, epoch):
        self.epoch = epoch

    def __iter__(self):
        n = len(self.dataset)
        if self.shuffle:
            rng = np.random.RandomState(self.epoch)
            indices = rng.permutation(n).tolist()
        else:
            indices = list(range(n))
        indices += indices[: self.total_size - len(indices)]
        indices = indices[self.local_rank:self.total_size:self.nranks]
        batch = []
        for idx in indices:
            batch.append(idx)
            if len(batch) == self.batch_size:
                yield batch
                batch = []
        if batch and not self.drop_last:
            yield batch

    def __len__(self):
        if self.drop_last:
            return self.num_samples // self.batch_size
        return (self.num_samples + self.batch_size - 1) // self.batch_size


# ---- collate / loader -----------------------------------------------------

class _TensorPayload:
    """Marks 'this numpy array becomes a Tensor in the trainer process'.
    Plain numpy arrays from a user collate_fn pass through untouched."""

    __slots__ = ("array",)

    def __init__(self, array):
        self.array = array


def _collate_impl(batch, stack, leaf):
    """One collate structure, two leaf constructors: Tensor in the trainer
    process (default_collate_fn), _TensorPayload in subprocess workers
    (_np_collate) — so the type dispatch can't silently diverge."""
    sample = batch[0]
    if isinstance(sample, Tensor):
        return leaf(stack([np.asarray(s._data) for s in batch]))
    if isinstance(sample, np.ndarray):
        return leaf(stack(list(batch)))
    if isinstance(sample, (int, float, np.integer, np.floating)):
        return leaf(np.asarray(batch))
    if isinstance(sample, (str, bytes)):
        return list(batch)
    if isinstance(sample, dict):
        return {k: _collate_impl([s[k] for s in batch], stack, leaf)
                for k in sample}
    if isinstance(sample, (list, tuple)):
        return type(sample)(_collate_impl(list(items), stack, leaf)
                            for items in zip(*batch))
    return batch


def default_collate_fn(batch):
    from ..native import parallel_stack
    return _collate_impl(batch, parallel_stack, Tensor)


def default_convert_fn(batch):
    """Reference ``paddle.io.dataloader.collate.default_convert_fn``
    surface: convert array-likes to Tensors WITHOUT stacking a batch
    dim (the collate used when ``DataLoader(batch_size=None)`` hands
    samples through unbatched)."""
    if isinstance(batch, (Tensor,)):
        return batch
    if isinstance(batch, (np.ndarray, np.integer, np.floating)):
        import jax.numpy as jnp
        return Tensor(jnp.asarray(batch))
    if isinstance(batch, (int, float)):
        return batch
    if isinstance(batch, tuple) and hasattr(batch, "_fields"):
        return type(batch)(*(default_convert_fn(b) for b in batch))
    if isinstance(batch, (list, tuple)):
        return type(batch)(default_convert_fn(b) for b in batch)
    if isinstance(batch, dict):
        return {k: default_convert_fn(v) for k, v in batch.items()}
    return batch


def _np_collate(batch):
    """default_collate_fn's structure, host-side only: workers stack with
    numpy and never create device arrays."""
    return _collate_impl(batch, np.stack, _TensorPayload)


class _WorkerInfo:
    def __init__(self, id, num_workers, dataset):
        self.id = id
        self.num_workers = num_workers
        self.dataset = dataset


_worker_info = threading.local()


def get_worker_info():
    return getattr(_worker_info, "info", None)


# ---- subprocess workers ----------------------------------------------------

class _WorkersDied(RuntimeError):
    """Subprocess worker(s) exited without reporting a result.

    Carries WHICH worker died first, its exit code, and the last
    traceback any worker managed to forward before dying — a
    one-worker OOM (SIGKILL, exit code -9) must surface as exactly
    that, not stall the epoch or read as an all-workers mystery."""

    def __init__(self, dead=(), last_tb=None, all_dead=False):
        self.dead = list(dead)            # [(worker_id, exitcode)]
        self.last_tb = last_tb
        self.all_dead = bool(all_dead)
        wid, code = (self.dead[0] if self.dead else (None, None))
        self.worker_id = wid
        self.exitcode = code
        msg = (f"DataLoader worker {wid} exited unexpectedly "
               f"(exit code {code}"
               + (", likely killed — e.g. OOM" if isinstance(code, int)
                  and code < 0 else "") + ")"
               if self.dead else
               "DataLoader subprocess workers exited unexpectedly")
        if len(self.dead) > 1:
            msg += f"; {len(self.dead)} workers dead: {self.dead}"
        if last_tb:
            msg += f"\nlast worker traceback:\n{last_tb}"
        super().__init__(msg)


def _encode_for_ipc(obj):
    """Tensor -> _TensorPayload (device arrays can't cross processes)."""
    if isinstance(obj, Tensor):
        return _TensorPayload(np.asarray(obj._data))
    if isinstance(obj, dict):
        return {k: _encode_for_ipc(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return type(obj)(_encode_for_ipc(v) for v in obj)
    return obj


def _decode_from_ipc(obj):
    if isinstance(obj, _TensorPayload):
        return Tensor(obj.array)
    if isinstance(obj, dict):
        return {k: _decode_from_ipc(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return type(obj)(_decode_from_ipc(v) for v in obj)
    return obj


def _mp_worker_loop(dataset, index_q, result_q, user_collate, wid,
                    num_workers, worker_init_fn):
    """Subprocess body: pull (epoch, batch_idx, indices) jobs, push
    (epoch, batch_idx, ok, payload) results. Pins jax (if anything in the
    worker imports it) to CPU — a data worker must never grab the TPU."""
    os.environ["JAX_PLATFORMS"] = "cpu"
    try:
        import jax

        jax.config.update("jax_platforms", "cpu")
    except Exception:
        pass
    import traceback

    try:
        _worker_info.info = _WorkerInfo(wid, num_workers, dataset)
        if worker_init_fn is not None:
            worker_init_fn(wid)
        collate = user_collate if user_collate is not None else _np_collate
        while True:
            job = index_q.get()
            if job is None:
                return
            epoch, bidx, indices = job
            try:
                out = collate([dataset[i] for i in indices])
                if user_collate is not None:
                    out = _encode_for_ipc(out)
                result_q.put((epoch, bidx, True, out))
            except Exception as e:  # noqa: BLE001 — forwarded to the trainer
                try:
                    pickle.dumps(e)
                    payload = (e, traceback.format_exc())
                except Exception:
                    payload = (None, traceback.format_exc())
                result_q.put((epoch, bidx, False, payload))
    except BaseException:  # noqa: BLE001 — loop-level crash (init,
        # queue plumbing, KeyboardInterrupt): forward the traceback so
        # the trainer can attribute the death, then let the process die
        try:
            result_q.put(("__worker_crash__", wid,
                          traceback.format_exc()))
        except Exception:
            pass
        raise


class _SpawnPool:
    """num_workers spawn-context processes around one index queue and one
    result queue (the reference DataLoader's process model)."""

    def __init__(self, dataset, user_collate, num_workers, worker_init_fn):
        ctx = multiprocessing.get_context("spawn")
        self.index_q = ctx.Queue()
        self.result_q = ctx.Queue()
        self.workers = []
        self.last_crash_tb = None   # most recent forwarded crash tb
        # children inherit the environment at start(): pin them to CPU jax
        # from interpreter startup (before any unpickling can touch jax)
        prev = os.environ.get("JAX_PLATFORMS")
        os.environ["JAX_PLATFORMS"] = "cpu"
        try:
            for wid in range(num_workers):
                p = ctx.Process(
                    target=_mp_worker_loop,
                    args=(dataset, self.index_q, self.result_q,
                          user_collate, wid, num_workers, worker_init_fn),
                    daemon=True)
                p.start()
                self.workers.append(p)
        except Exception:
            self.shutdown()
            raise
        finally:
            if prev is None:
                os.environ.pop("JAX_PLATFORMS", None)
            else:
                os.environ["JAX_PLATFORMS"] = prev

    def alive(self):
        return all(p.is_alive() for p in self.workers)

    def dead(self):
        """[(worker_id, exitcode)] for workers that have exited."""
        return [(wid, p.exitcode) for wid, p in enumerate(self.workers)
                if not p.is_alive()]

    def shutdown(self):
        for _ in self.workers:
            try:
                self.index_q.put(None)
            except Exception:
                pass
        for p in self.workers:
            p.join(timeout=5)
            if p.is_alive():
                p.terminate()
        for q_ in (self.index_q, self.result_q):
            try:
                q_.close()
                q_.cancel_join_thread()
            except Exception:
                pass


class _PrefetchFailure:
    """Producer-thread exception in flight to the consumer."""

    __slots__ = ("exc", "tb")

    def __init__(self, exc, tb):
        self.exc = exc
        self.tb = tb


class DevicePrefetcher:
    """Background device-placement stage for the training hot path.

    Wraps any batch iterator: a daemon thread pulls batches AHEAD of
    the consumer (double-buffered, bounded by ``depth``) and places
    every array leaf on device with ``jax.device_put`` — so host-side
    dataset work, collation and the H2D copy of batch k+1..k+depth
    overlap the consumer's step k. ``sharding`` (any
    ``jax.sharding.Sharding``, e.g. a ``NamedSharding`` over a ``dp``
    mesh axis) makes placement sharding-aware: each GLOBAL batch lands
    split across the mesh directly from host memory, no host-side
    gather and no per-device python loop; ``None`` places on the
    default device.

    Overlap accounting (the profiler's ``input_wait_ms`` gauge):
    ``input_wait_s`` accumulates only the time the CONSUMER blocked in
    ``__next__`` — 0 means the pipeline was never the bottleneck;
    ``h2d_bytes`` counts bytes placed; ``batches`` batches delivered.
    """

    _END = object()

    def __init__(self, it, depth: int = 2, sharding=None):
        self.depth = max(int(depth), 1)
        self.sharding = sharding
        self.input_wait_s = 0.0
        self.h2d_bytes = 0
        self.batches = 0
        self._q: queue.Queue = queue.Queue(maxsize=self.depth)
        self._stop = threading.Event()
        self._done = False
        self._thread = threading.Thread(
            target=self._produce, args=(iter(it),), daemon=True)
        self._thread.start()

    # -- producer side -----------------------------------------------------

    def _place_leaf(self, data):
        import jax
        if self.sharding is not None:
            placed = jax.device_put(data, self.sharding)
        else:
            placed = jax.device_put(data)
        self.h2d_bytes += int(getattr(placed, "nbytes", 0) or 0)
        return placed

    def _place(self, obj):
        if isinstance(obj, Tensor):
            return Tensor(self._place_leaf(obj._data))
        if isinstance(obj, np.ndarray):
            return Tensor(self._place_leaf(obj))
        if isinstance(obj, dict):
            return {k: self._place(v) for k, v in obj.items()}
        if isinstance(obj, tuple) and hasattr(obj, "_fields"):
            return type(obj)(*(self._place(v) for v in obj))
        if isinstance(obj, (list, tuple)):
            return type(obj)(self._place(v) for v in obj)
        return obj

    def _offer(self, item) -> bool:
        """Bounded put that stays responsive to close(); False when the
        consumer went away."""
        while not self._stop.is_set():
            try:
                self._q.put(item, timeout=0.2)
                return True
            except queue.Full:
                continue
        return False

    def _produce(self, it):
        try:
            for b in it:
                if self._stop.is_set():
                    return
                if not self._offer(self._place(b)):
                    return
            self._offer(self._END)
        except BaseException as e:  # noqa: BLE001 — forwarded to consumer
            import traceback
            self._offer(_PrefetchFailure(e, traceback.format_exc()))

    # -- consumer side -----------------------------------------------------

    def __iter__(self):
        return self

    def __next__(self):
        if self._done:
            # exhausted iterators must KEEP raising StopIteration — a
            # blind q.get() here would block forever (producer gone)
            raise StopIteration
        t0 = time.perf_counter()
        item = self._q.get()
        self.input_wait_s += time.perf_counter() - t0
        if item is self._END:
            self._done = True
            raise StopIteration
        if isinstance(item, _PrefetchFailure):
            self._done = True
            raise item.exc from RuntimeError(
                f"DevicePrefetcher producer failed:\n{item.tb}")
        self.batches += 1
        return item

    def close(self):
        self._stop.set()
        self._done = True   # a closed iterator must raise, not block
        # unblock a producer stuck on a full queue
        try:
            self._q.get_nowait()
        except queue.Empty:
            pass

    def __del__(self):
        self.close()


def device_prefetch(it, depth: int = 2, sharding=None) -> DevicePrefetcher:
    """Wrap ``it`` in a :class:`DevicePrefetcher` (see its docstring)."""
    return DevicePrefetcher(it, depth=depth, sharding=sharding)


class DataLoader:
    def __init__(self, dataset, feed_list=None, places=None,
                 return_list=True, batch_sampler=None, batch_size=1,
                 shuffle=False, drop_last=False, collate_fn=None,
                 num_workers=0, use_buffer_reader=True, prefetch_factor=2,
                 use_shared_memory=True, timeout=0, worker_init_fn=None,
                 persistent_workers=False, prefetch_to_device=None,
                 device_sharding=None):
        self.dataset = dataset
        self.collate_fn = collate_fn or default_collate_fn
        self.num_workers = num_workers
        self.prefetch_factor = prefetch_factor
        self.worker_init_fn = worker_init_fn
        self.timeout = timeout
        self.persistent_workers = persistent_workers
        # device-prefetch stage (DevicePrefetcher): depth of batches
        # placed on device ahead of the consumer; device_sharding is a
        # jax Sharding for DP-sharded global-batch placement
        self.prefetch_to_device = prefetch_to_device
        self.device_sharding = device_sharding
        self._pool: _SpawnPool | None = None
        self._pool_active = False  # persistent pool owned by a live iter
        self._pool_owner = None    # weakref to the owning iterator
        self._mp_broken = False   # spawn failed once -> stay on threads
        self._epoch = 0
        self._iterable = isinstance(dataset, IterableDataset)
        # batch_size=None = NO batching (reference semantics): samples
        # pass through one by one, converted (not stacked) by
        # default_convert_fn unless the caller supplied a collate_fn
        self._unbatched = batch_size is None and batch_sampler is None
        if self._unbatched and collate_fn is None:
            self.collate_fn = default_convert_fn
        if self._iterable:
            self.batch_sampler = None
            self.batch_size = batch_size
            self.drop_last = drop_last
        elif batch_sampler is not None:
            self.batch_sampler = batch_sampler
        elif self._unbatched:
            self.batch_sampler = None
        else:
            self.batch_sampler = BatchSampler(
                dataset, shuffle=shuffle, batch_size=batch_size,
                drop_last=drop_last)

    def __len__(self):
        if self._iterable:
            raise TypeError("IterableDataset has no length")
        if self._unbatched:
            return len(self.dataset)
        return len(self.batch_sampler)

    def _iter_batches(self) -> Iterator:
        if self._iterable:
            if self._unbatched:
                for item in self.dataset:
                    yield self.collate_fn(item)
                return
            batch = []
            for item in self.dataset:
                batch.append(item)
                if len(batch) == self.batch_size:
                    yield self.collate_fn(batch)
                    batch = []
            if batch and not self.drop_last:
                yield self.collate_fn(batch)
            return
        if self._unbatched:
            for i in range(len(self.dataset)):
                yield self.collate_fn(self.dataset[i])
            return
        for indices in self.batch_sampler:
            yield self.collate_fn([self.dataset[i] for i in indices])

    def __iter__(self):
        it = self._make_iter()
        if self.prefetch_to_device:
            it = DevicePrefetcher(it, depth=self.prefetch_to_device,
                                  sharding=self.device_sharding)
        return it

    def _make_iter(self):
        if self.num_workers <= 0 or self._unbatched:
            # unbatched pass-through is pure conversion — worker
            # processes would only add transport cost
            return self._iter_batches()
        if self._iterable:
            return self._iter_prefetch_single()
        if self._mp_broken:
            return self._iter_pool()
        import weakref
        if (self._pool is not None and self._pool_active
                and self._pool_owner is not None
                and self._pool_owner() is None):
            # the iterator that CLAIMED the pool is gone but its finally
            # never reset the flag (e.g. close() raised, or a reference
            # cycle delayed collection past the flag check) — reclaim the
            # persistent pool instead of silently demoting every
            # subsequent epoch to a transient per-epoch spawn pool
            self._pool_active = False
        owner_box: list = []
        g = self._iter_mp(owner_box)
        # the generator stores this ref as _pool_owner only if/when it
        # actually claims the persistent pool (inside _iter_mp) — setting
        # it here for every iterator would let a later never-started
        # iterator usurp ownership from the live claimant
        owner_box.append(weakref.ref(g))
        return g

    def __del__(self):
        pool, self._pool = self._pool, None
        if pool is not None:
            pool.shutdown()

    def _iter_prefetch_single(self):
        """IterableDataset path: one background producer thread (the stream
        itself is sequential), bounded prefetch queue."""
        q: queue.Queue = queue.Queue(
            maxsize=self.num_workers * self.prefetch_factor)
        stop = object()

        def producer():
            _worker_info.info = _WorkerInfo(0, self.num_workers,
                                            self.dataset)
            if self.worker_init_fn is not None:
                self.worker_init_fn(0)
            try:
                for b in self._iter_batches():
                    q.put(b)
            finally:
                q.put(stop)

        t = threading.Thread(target=producer, daemon=True)
        t.start()
        while True:
            b = q.get()
            if b is stop:
                break
            yield b
        t.join()

    # ---- subprocess path (map-style, the default) ------------------------

    def _iter_mp(self, owner_box=None):
        """Map-style path: num_workers subprocesses; jobs are
        (epoch, batch_idx, indices); results reassemble strictly in
        batch-sampler order with a bounded in-flight window."""
        pool = self._pool
        # a second concurrent iterator must not share the persistent
        # pool's result queue (it would steal/drop the first's batches) —
        # give it a transient pool of its own
        transient = pool is not None and self._pool_active
        if transient:
            pool = None
        if pool is None:
            user_collate = (None if self.collate_fn is default_collate_fn
                            else self.collate_fn)
            try:
                pool = _SpawnPool(self.dataset, user_collate,
                                  self.num_workers, self.worker_init_fn)
            except Exception as e:
                warnings.warn(
                    f"DataLoader: could not spawn subprocess workers "
                    f"({type(e).__name__}: {e}); the dataset/collate_fn/"
                    f"worker_init_fn must be picklable. Falling back to "
                    f"the prefetch thread pool.")
                self._mp_broken = True
                yield from self._iter_pool()
                return
        persist = self.persistent_workers and not transient
        if persist:
            self._pool = pool
            self._pool_active = True
            if owner_box:
                self._pool_owner = owner_box[0]
        self._epoch += 1
        epoch = self._epoch
        window = max(self.num_workers * self.prefetch_factor, 1)
        it = iter(self.batch_sampler)
        it_done = False
        submitted = 0
        next_yield = 0
        buf = {}
        fall_back = False

        def refill():
            nonlocal submitted, it_done
            if it_done:
                return
            try:
                pool.index_q.put((epoch, submitted, list(next(it))))
                submitted += 1
            except StopIteration:
                it_done = True

        try:
            while submitted < window and not it_done:
                refill()
            while next_yield < submitted or not it_done:
                if next_yield in buf:
                    b = buf.pop(next_yield)
                    next_yield += 1
                    refill()
                    yield b
                    continue
                try:
                    ep, bidx, ok, payload = self._result_get(pool)
                except _WorkersDied as wd:
                    if next_yield == 0 and not buf:
                        # death before ANY result. All-dead means the
                        # dataset failed to unpickle in the fresh
                        # interpreter — the thread pool can still
                        # serve. Bootstrap crashes land staggered, so
                        # give the remaining children a moment to
                        # finish dying before deciding all-dead
                        # (fallback) vs genuinely partial (a hard
                        # error carrying the worker's exit code — a
                        # one-worker OOM must never re-run its killer
                        # item in the trainer process).
                        deadline = time.time() + 2.0
                        while (len(pool.dead()) < len(pool.workers)
                               and time.time() < deadline):
                            time.sleep(0.05)
                        codes = [c for _, c in pool.dead()]
                        if len(codes) == len(pool.workers) and \
                                all(c == 1 for c in codes):
                            # uniform exit-1 = a python exception in
                            # the spawn bootstrap (unpickle/init), the
                            # one shape the thread pool can safely
                            # retry in-process. Signal kills (OOM) or
                            # explicit exit codes mean an ITEM killed
                            # the worker — retrying it in the trainer
                            # would kill the trainer.
                            fall_back = True
                            break
                    raise wd from None
                if ep != epoch:   # stale result from an abandoned epoch
                    continue
                if not ok:
                    exc, tb = payload
                    if exc is not None:
                        raise exc from RuntimeError(
                            f"DataLoader worker failed:\n{tb}")
                    raise RuntimeError(f"DataLoader worker failed:\n{tb}")
                buf[bidx] = _decode_from_ipc(payload)
        finally:
            if persist:
                self._pool_active = False
            if not persist or fall_back:
                if pool is self._pool:
                    self._pool = None
                pool.shutdown()
        if fall_back:
            warnings.warn(
                "DataLoader subprocess workers died during startup (the "
                "dataset may not survive re-import in a spawned "
                "interpreter); falling back to the prefetch thread pool.")
            self._mp_broken = True
            yield from self._iter_pool()

    def _result_get(self, pool):
        deadline = time.time() + self.timeout if self.timeout else None
        while True:
            try:
                item = pool.result_q.get(timeout=1.0)
            except queue.Empty:
                dead = pool.dead()
                if dead:
                    # drain in-flight crash notices first so the error
                    # carries the dying worker's own traceback (the
                    # ordered reassembly is moot — we are raising)
                    try:
                        while True:
                            it2 = pool.result_q.get_nowait()
                            if isinstance(it2, tuple) and len(it2) == 3 \
                                    and it2[0] == "__worker_crash__":
                                pool.last_crash_tb = it2[2]
                    except queue.Empty:
                        pass
                    raise _WorkersDied(
                        dead, getattr(pool, "last_crash_tb", None),
                        all_dead=len(dead) == len(pool.workers)) from None
                if deadline is not None and time.time() > deadline:
                    raise RuntimeError(
                        f"DataLoader timed out after {self.timeout}s "
                        "waiting for a worker batch") from None
                continue
            if isinstance(item, tuple) and len(item) == 3 \
                    and item[0] == "__worker_crash__":
                # remember the traceback; the death itself is detected
                # (with exit code) once the queue runs dry
                pool.last_crash_tb = item[2]
                continue
            return item

    def _iter_pool(self):
        """Map-style path: num_workers threads load batches concurrently
        (numpy/PIL/IO release the GIL), results yielded strictly in
        batch-sampler order with a bounded in-flight window."""
        from concurrent.futures import ThreadPoolExecutor

        window = max(self.num_workers * self.prefetch_factor, 1)

        def init_worker(wid=[0]):
            with self._pool_lock:
                my_id = wid[0]
                wid[0] += 1
            _worker_info.info = _WorkerInfo(my_id, self.num_workers,
                                            self.dataset)
            if self.worker_init_fn is not None:
                self.worker_init_fn(my_id)

        def load(indices):
            if getattr(_worker_info, "info", None) is None:
                init_worker()
            return self.collate_fn([self.dataset[i] for i in indices])

        self._pool_lock = threading.Lock()
        with ThreadPoolExecutor(max_workers=self.num_workers) as pool:
            futures = collections.deque()
            it = iter(self.batch_sampler)
            try:
                for _ in range(window):
                    futures.append(pool.submit(load, next(it)))
            except StopIteration:
                it = None
            while futures:
                yield futures.popleft().result()
                if it is not None:
                    try:
                        futures.append(pool.submit(load, next(it)))
                    except StopIteration:
                        it = None


class SubsetRandomSampler(Sampler):
    """Sample the given indices in random order (paddle.io parity).
    Reproducible per epoch via the same seeded-RandomState convention as
    RandomSampler above."""

    def __init__(self, indices):
        self.indices = list(indices)
        self._epoch_seed = itertools.count()

    def __iter__(self):
        rng = np.random.RandomState(next(self._epoch_seed) + 12345)
        return iter([self.indices[i]
                     for i in rng.permutation(len(self.indices))])

    def __len__(self):
        return len(self.indices)


__all__.append("SubsetRandomSampler")
