"""``paddle.io`` — Dataset / DataLoader / samplers
(python/paddle/io/ parity, UNVERIFIED).

TPU-first notes: the DataLoader feeds numpy batches converted to jax arrays;
worker parallelism uses threads (jax arrays are produced on the host side
anyway, and XLA transfers overlap with compute). ``num_workers`` > 0 uses a
background prefetch thread pool rather than fork-based workers."""

from __future__ import annotations

import collections
import itertools
import math
import queue
import threading
from typing import Iterable, Iterator

import numpy as np

from ..framework.core import Tensor
from ..framework import random as framework_random

__all__ = ["Dataset", "IterableDataset", "TensorDataset", "ComposeDataset",
           "ChainDataset", "ConcatDataset", "Subset", "random_split",
           "Sampler", "SequenceSampler", "RandomSampler", "BatchSampler",
           "WeightedRandomSampler", "DistributedBatchSampler", "DataLoader",
           "get_worker_info", "default_collate_fn"]


class Dataset:
    def __getitem__(self, idx):
        raise NotImplementedError

    def __len__(self):
        raise NotImplementedError


class IterableDataset(Dataset):
    def __iter__(self):
        raise NotImplementedError

    def __getitem__(self, idx):
        raise RuntimeError("IterableDataset does not support indexing")

    def __len__(self):
        raise RuntimeError("IterableDataset has no len()")


class TensorDataset(Dataset):
    def __init__(self, tensors):
        self.tensors = tensors

    def __getitem__(self, idx):
        return tuple(t[idx] for t in self.tensors)

    def __len__(self):
        return self.tensors[0].shape[0]


class ComposeDataset(Dataset):
    def __init__(self, datasets):
        self.datasets = list(datasets)

    def __len__(self):
        return min(len(d) for d in self.datasets)

    def __getitem__(self, idx):
        out = []
        for d in self.datasets:
            item = d[idx]
            if isinstance(item, (list, tuple)):
                out.extend(item)
            else:
                out.append(item)
        return tuple(out)


class ChainDataset(IterableDataset):
    def __init__(self, datasets):
        self.datasets = list(datasets)

    def __iter__(self):
        for d in self.datasets:
            yield from d


class ConcatDataset(Dataset):
    def __init__(self, datasets):
        self.datasets = list(datasets)
        self.cum = np.cumsum([len(d) for d in self.datasets])

    def __len__(self):
        return int(self.cum[-1])

    def __getitem__(self, idx):
        ds_idx = int(np.searchsorted(self.cum, idx, side="right"))
        prev = 0 if ds_idx == 0 else int(self.cum[ds_idx - 1])
        return self.datasets[ds_idx][idx - prev]


class Subset(Dataset):
    def __init__(self, dataset, indices):
        self.dataset = dataset
        self.indices = list(indices)

    def __getitem__(self, idx):
        return self.dataset[self.indices[idx]]

    def __len__(self):
        return len(self.indices)


def random_split(dataset, lengths, generator=None):
    if all(isinstance(l, float) for l in lengths):
        n = len(dataset)
        lengths = [int(math.floor(n * f)) for f in lengths]
        lengths[-1] += n - sum(lengths)
    total = sum(lengths)
    perm = np.random.RandomState(0).permutation(total) if generator is None \
        else np.asarray(generator)
    out, off = [], 0
    for l in lengths:
        out.append(Subset(dataset, perm[off:off + l].tolist()))
        off += l
    return out


# ---- samplers -------------------------------------------------------------

class Sampler:
    def __init__(self, data_source=None):
        self.data_source = data_source

    def __iter__(self):
        raise NotImplementedError


class SequenceSampler(Sampler):
    def __iter__(self):
        return iter(range(len(self.data_source)))

    def __len__(self):
        return len(self.data_source)


class RandomSampler(Sampler):
    def __init__(self, data_source, replacement=False, num_samples=None,
                 generator=None):
        super().__init__(data_source)
        self.replacement = replacement
        self._num_samples = num_samples
        self.generator = generator
        self._epoch_seed = itertools.count()

    @property
    def num_samples(self):
        return self._num_samples or len(self.data_source)

    def __iter__(self):
        n = len(self.data_source)
        rng = np.random.RandomState(next(self._epoch_seed) + 12345)
        if self.replacement:
            return iter(rng.randint(0, n, self.num_samples).tolist())
        return iter(rng.permutation(n)[: self.num_samples].tolist())

    def __len__(self):
        return self.num_samples


class WeightedRandomSampler(Sampler):
    def __init__(self, weights, num_samples, replacement=True):
        self.weights = np.asarray(weights, dtype=np.float64)
        self.num_samples = num_samples
        self.replacement = replacement

    def __iter__(self):
        p = self.weights / self.weights.sum()
        idx = np.random.choice(len(self.weights), self.num_samples,
                               replace=self.replacement, p=p)
        return iter(idx.tolist())

    def __len__(self):
        return self.num_samples


class BatchSampler(Sampler):
    def __init__(self, dataset=None, sampler=None, shuffle=False,
                 batch_size=1, drop_last=False):
        self.batch_size = batch_size
        self.drop_last = drop_last
        if sampler is not None:
            self.sampler = sampler
        elif shuffle:
            self.sampler = RandomSampler(dataset)
        else:
            self.sampler = SequenceSampler(dataset)

    def __iter__(self):
        batch = []
        for idx in self.sampler:
            batch.append(idx)
            if len(batch) == self.batch_size:
                yield batch
                batch = []
        if batch and not self.drop_last:
            yield batch

    def __len__(self):
        n = len(self.sampler)
        if self.drop_last:
            return n // self.batch_size
        return (n + self.batch_size - 1) // self.batch_size


class DistributedBatchSampler(BatchSampler):
    """Shards the dataset across data-parallel ranks
    (fleet DistributedBatchSampler parity)."""

    def __init__(self, dataset, batch_size, num_replicas=None, rank=None,
                 shuffle=False, drop_last=False):
        self.dataset = dataset
        self.batch_size = batch_size
        self.shuffle = shuffle
        self.drop_last = drop_last
        if num_replicas is None or rank is None:
            from ..distributed import get_world_size, get_rank
            num_replicas = num_replicas or get_world_size()
            rank = rank if rank is not None else get_rank()
        self.nranks = num_replicas
        self.local_rank = rank
        self.epoch = 0
        self.num_samples = int(math.ceil(len(dataset) / self.nranks))
        self.total_size = self.num_samples * self.nranks

    def set_epoch(self, epoch):
        self.epoch = epoch

    def __iter__(self):
        n = len(self.dataset)
        if self.shuffle:
            rng = np.random.RandomState(self.epoch)
            indices = rng.permutation(n).tolist()
        else:
            indices = list(range(n))
        indices += indices[: self.total_size - len(indices)]
        indices = indices[self.local_rank:self.total_size:self.nranks]
        batch = []
        for idx in indices:
            batch.append(idx)
            if len(batch) == self.batch_size:
                yield batch
                batch = []
        if batch and not self.drop_last:
            yield batch

    def __len__(self):
        if self.drop_last:
            return self.num_samples // self.batch_size
        return (self.num_samples + self.batch_size - 1) // self.batch_size


# ---- collate / loader -----------------------------------------------------

def default_collate_fn(batch):
    sample = batch[0]
    if isinstance(sample, Tensor):
        from ..native import parallel_stack
        return Tensor(parallel_stack([np.asarray(s._data) for s in batch]))
    if isinstance(sample, np.ndarray):
        from ..native import parallel_stack
        return Tensor(parallel_stack(batch))
    if isinstance(sample, (int, float)):
        return Tensor(np.asarray(batch))
    if isinstance(sample, (str, bytes)):
        return batch
    if isinstance(sample, dict):
        return {k: default_collate_fn([s[k] for s in batch])
                for k in sample}
    if isinstance(sample, (list, tuple)):
        return type(sample)(default_collate_fn(list(items))
                            for items in zip(*batch))
    return batch


class _WorkerInfo:
    def __init__(self, id, num_workers, dataset):
        self.id = id
        self.num_workers = num_workers
        self.dataset = dataset


_worker_info = threading.local()


def get_worker_info():
    return getattr(_worker_info, "info", None)


class DataLoader:
    def __init__(self, dataset, feed_list=None, places=None,
                 return_list=True, batch_sampler=None, batch_size=1,
                 shuffle=False, drop_last=False, collate_fn=None,
                 num_workers=0, use_buffer_reader=True, prefetch_factor=2,
                 use_shared_memory=True, timeout=0, worker_init_fn=None,
                 persistent_workers=False):
        self.dataset = dataset
        self.collate_fn = collate_fn or default_collate_fn
        self.num_workers = num_workers
        self.prefetch_factor = prefetch_factor
        self.worker_init_fn = worker_init_fn
        self._iterable = isinstance(dataset, IterableDataset)
        if self._iterable:
            self.batch_sampler = None
            self.batch_size = batch_size
            self.drop_last = drop_last
        elif batch_sampler is not None:
            self.batch_sampler = batch_sampler
        else:
            self.batch_sampler = BatchSampler(
                dataset, shuffle=shuffle, batch_size=batch_size,
                drop_last=drop_last)

    def __len__(self):
        if self._iterable:
            raise TypeError("IterableDataset has no length")
        return len(self.batch_sampler)

    def _iter_batches(self) -> Iterator:
        if self._iterable:
            batch = []
            for item in self.dataset:
                batch.append(item)
                if len(batch) == self.batch_size:
                    yield self.collate_fn(batch)
                    batch = []
            if batch and not self.drop_last:
                yield self.collate_fn(batch)
            return
        for indices in self.batch_sampler:
            yield self.collate_fn([self.dataset[i] for i in indices])

    def __iter__(self):
        if self.num_workers <= 0:
            yield from self._iter_batches()
            return
        if self._iterable:
            yield from self._iter_prefetch_single()
            return
        yield from self._iter_pool()

    def _iter_prefetch_single(self):
        """IterableDataset path: one background producer thread (the stream
        itself is sequential), bounded prefetch queue."""
        q: queue.Queue = queue.Queue(
            maxsize=self.num_workers * self.prefetch_factor)
        stop = object()

        def producer():
            _worker_info.info = _WorkerInfo(0, self.num_workers,
                                            self.dataset)
            if self.worker_init_fn is not None:
                self.worker_init_fn(0)
            try:
                for b in self._iter_batches():
                    q.put(b)
            finally:
                q.put(stop)

        t = threading.Thread(target=producer, daemon=True)
        t.start()
        while True:
            b = q.get()
            if b is stop:
                break
            yield b
        t.join()

    def _iter_pool(self):
        """Map-style path: num_workers threads load batches concurrently
        (numpy/PIL/IO release the GIL), results yielded strictly in
        batch-sampler order with a bounded in-flight window."""
        from concurrent.futures import ThreadPoolExecutor

        window = max(self.num_workers * self.prefetch_factor, 1)

        def init_worker(wid=[0]):
            with self._pool_lock:
                my_id = wid[0]
                wid[0] += 1
            _worker_info.info = _WorkerInfo(my_id, self.num_workers,
                                            self.dataset)
            if self.worker_init_fn is not None:
                self.worker_init_fn(my_id)

        def load(indices):
            if getattr(_worker_info, "info", None) is None:
                init_worker()
            return self.collate_fn([self.dataset[i] for i in indices])

        self._pool_lock = threading.Lock()
        with ThreadPoolExecutor(max_workers=self.num_workers) as pool:
            futures = collections.deque()
            it = iter(self.batch_sampler)
            try:
                for _ in range(window):
                    futures.append(pool.submit(load, next(it)))
            except StopIteration:
                it = None
            while futures:
                yield futures.popleft().result()
                if it is not None:
                    try:
                        futures.append(pool.submit(load, next(it)))
                    except StopIteration:
                        it = None
