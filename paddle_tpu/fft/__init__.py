"""``paddle.fft`` — FFT family (python/paddle/fft.py parity, UNVERIFIED;
SURVEY.md §2.2 tensor-ops row). Thin differentiable wrappers over
jnp.fft — XLA lowers these to the TPU FFT HLO."""

from __future__ import annotations

import jax.numpy as jnp

from ..framework.core import Tensor, apply
from ..ops.common import as_tensor

__all__ = ["fft", "ifft", "fft2", "ifft2", "fftn", "ifftn",
           "rfft", "irfft", "rfft2", "irfft2", "rfftn", "irfftn",
           "hfft", "ihfft", "hfft2", "ihfft2", "hfftn", "ihfftn",
           "fftfreq", "rfftfreq", "fftshift", "ifftshift"]


def _norm(norm):
    # paddle uses 'backward' | 'forward' | 'ortho' like numpy
    return norm if norm is not None else "backward"


def _wrap1(jfn, opname):
    def op(x, n=None, axis=-1, norm="backward", name=None):
        return apply(lambda a: jfn(a, n=n, axis=axis, norm=_norm(norm)),
                     as_tensor(x), name=opname)
    op.__name__ = opname
    return op


def _wrapn(jfn, opname):
    def op(x, s=None, axes=None, norm="backward", name=None):
        kw = {"s": s, "axes": axes, "norm": _norm(norm)}
        return apply(lambda a: jfn(a, **kw), as_tensor(x), name=opname)
    op.__name__ = opname
    return op


fft = _wrap1(jnp.fft.fft, "fft")
ifft = _wrap1(jnp.fft.ifft, "ifft")
rfft = _wrap1(jnp.fft.rfft, "rfft")
irfft = _wrap1(jnp.fft.irfft, "irfft")
hfft = _wrap1(jnp.fft.hfft, "hfft")
ihfft = _wrap1(jnp.fft.ihfft, "ihfft")

fftn = _wrapn(jnp.fft.fftn, "fftn")
ifftn = _wrapn(jnp.fft.ifftn, "ifftn")
rfftn = _wrapn(jnp.fft.rfftn, "rfftn")
irfftn = _wrapn(jnp.fft.irfftn, "irfftn")


def fft2(x, s=None, axes=(-2, -1), norm="backward", name=None):
    return fftn(x, s=s, axes=axes, norm=norm)


def ifft2(x, s=None, axes=(-2, -1), norm="backward", name=None):
    return ifftn(x, s=s, axes=axes, norm=norm)


def rfft2(x, s=None, axes=(-2, -1), norm="backward", name=None):
    return rfftn(x, s=s, axes=axes, norm=norm)


def irfft2(x, s=None, axes=(-2, -1), norm="backward", name=None):
    return irfftn(x, s=s, axes=axes, norm=norm)


def _swap_norm(norm):
    # Hermitian transforms are the real transforms with time/frequency
    # domains swapped, so 'backward' and 'forward' normalization swap too
    # (ortho is self-dual) — the numpy/scipy hfft identity.
    return {"backward": "forward", "forward": "backward"}[_norm(norm)] \
        if _norm(norm) != "ortho" else "ortho"


def hfftn(x, s=None, axes=None, norm="backward", name=None):
    """FFT of a signal with Hermitian symmetry over ``axes`` → real
    output. hfftn(x) == irfftn(conj(x)) under the swapped norm."""
    def fn(a):
        return jnp.fft.irfftn(jnp.conj(a), s=s, axes=axes,
                              norm=_swap_norm(norm))
    return apply(fn, as_tensor(x), name="hfftn")


def ihfftn(x, s=None, axes=None, norm="backward", name=None):
    def fn(a):
        return jnp.conj(jnp.fft.rfftn(a, s=s, axes=axes,
                                      norm=_swap_norm(norm)))
    return apply(fn, as_tensor(x), name="ihfftn")


def hfft2(x, s=None, axes=(-2, -1), norm="backward", name=None):
    return hfftn(x, s=s, axes=axes, norm=norm)


def ihfft2(x, s=None, axes=(-2, -1), norm="backward", name=None):
    return ihfftn(x, s=s, axes=axes, norm=norm)


def fftfreq(n, d=1.0, dtype=None, name=None):
    out = jnp.fft.fftfreq(n, d)
    return Tensor(out.astype(dtype) if dtype else out)


def rfftfreq(n, d=1.0, dtype=None, name=None):
    out = jnp.fft.rfftfreq(n, d)
    return Tensor(out.astype(dtype) if dtype else out)


def fftshift(x, axes=None, name=None):
    return apply(lambda a: jnp.fft.fftshift(a, axes=axes), as_tensor(x),
                 name="fftshift")


def ifftshift(x, axes=None, name=None):
    return apply(lambda a: jnp.fft.ifftshift(a, axes=axes), as_tensor(x),
                 name="ifftshift")
