"""paddle_tpu.testing — test-support utilities.

``fault_injection`` deterministically injects filesystem faults
(ENOSPC/EIO, partial writes, crash/pause at a chosen operation) so the
checkpoint crash-safety guarantees are proven by tests instead of
asserted in docstrings. See docs/checkpoint_fault_tolerance.md.
"""

from .fault_injection import FaultInjector, FaultPlan

__all__ = ["FaultInjector", "FaultPlan"]
