"""Deterministic filesystem fault injection.

Robustness claims ("a save killed mid-write never yields a loadable
checkpoint") are only worth anything if a test can *produce* the fault
on demand. ``FaultInjector`` patches ``builtins.open`` (which numpy's
``np.save``/``np.load`` also route through) plus ``os.replace`` /
``os.rename``, and fires registered :class:`FaultPlan`\\ s when an
operation touches a matching path:

- ``action="raise"`` — raise ``OSError(errno)`` (ENOSPC, EIO, ...),
  optionally after ``after_bytes`` of a write landed (a partial write
  followed by the error, the torn-write shape).
- ``action="truncate"`` — write only ``after_bytes`` bytes but report
  full success: the silent short write that only checksums catch.
- ``action="crash"`` — ``os._exit(41)``: abrupt process death at an
  exact operation, indistinguishable from SIGKILL to an observer (no
  atexit, no buffer flush, no cleanup).
- ``action="pause"`` — touch ``marker`` then sleep forever, so a
  parent test process can deliver a *real* SIGKILL at a known point
  (e.g. between shard write and commit).
- ``action="sigterm"`` — deliver a real SIGTERM to this process at the
  matching operation, then let the operation PROCEED: the preemption
  shape (the signal is asynchronous; work continues until the loop's
  next step boundary polls its ``PreemptionGuard``). The
  :meth:`FaultInjector.preempt` helper arms it.

Beyond filesystem ops, **call-site plans** (:meth:`FaultInjector
.fail_call` / :meth:`crash_call`) patch a dotted callable — e.g. the
optimizer step or a collective — and fire after ``after_calls``
invocations. That is how a chaos test kills a worker *mid-step* or
*mid-collective* at a chosen, randomizable point::

    fi.crash_call("paddle_tpu.distributed.communication.all_reduce")
    fi.crash_call("paddle_tpu.optimizer.optimizer.Optimizer.step",
                  after_calls=k)     # SIGKILL-equivalent at step k

Plans match by substring of the path and fire deterministically: each
plan fires at most ``times`` times, in registration order. Use as a
context manager so ``builtins.open`` is always restored::

    with FaultInjector() as fi:
        fi.fail("w.r0.s0.npy", op="write", errno_=errno.ENOSPC)
        save_state_dict(sd, path)     # first write ENOSPCs, retry wins
        assert fi.fires() == 1
"""

from __future__ import annotations

import builtins
import errno as _errno
import importlib
import os
import signal as _signal
import threading
import time

__all__ = ["FaultInjector", "FaultPlan"]


class FaultPlan:
    """One armed fault: fires when ``op`` touches a path containing
    ``match``, at most ``times`` times."""

    def __init__(self, match, op="write", errno_=_errno.EIO, times=1,
                 after_bytes=0, action="raise", marker=None,
                 after_calls=0):
        if op not in ("open", "write", "read", "rename", "call"):
            raise ValueError(f"unknown fault op {op!r}")
        if action not in ("raise", "truncate", "crash", "pause",
                          "sigterm"):
            raise ValueError(f"unknown fault action {action!r}")
        self.match = match
        self.op = op
        self.errno = errno_
        self.times = int(times)
        self.after_bytes = int(after_bytes)
        self.after_calls = int(after_calls)
        self.action = action
        self.marker = marker
        self.fired = 0
        self.calls = 0

    def __repr__(self):
        return (f"FaultPlan({self.match!r}, op={self.op}, "
                f"action={self.action}, fired={self.fired}/{self.times})")


class _FaultFile:
    """File proxy that consults the injector on write()/read()."""

    def __init__(self, f, path, injector):
        self._f = f
        self._path = path
        self._inj = injector
        self._written = 0
        self._truncated = False

    def write(self, data):
        if self._truncated:
            return len(data)  # silently dropped tail of a short write
        plan = self._inj._take(self._path, "write",
                               pending=self._written + len(data))
        if plan is not None:
            if plan.action == "sigterm":
                # preemption notice mid-write: signal, then the write
                # itself PROCEEDS untouched (the signal is async)
                self._inj._act(plan, self._path)
            else:
                keep = max(0, plan.after_bytes - self._written)
                if keep:
                    self._f.write(data[:keep])
                    self._written += keep
                if plan.action == "truncate":
                    self._truncated = True
                    return len(data)  # lie: report full success
                self._inj._act(plan, self._path)  # raise/crash/pause
        n = self._f.write(data)
        self._written += len(data)
        return n

    def read(self, *args):
        plan = self._inj._take(self._path, "read")
        if plan is not None:
            self._inj._act(plan, self._path)
        return self._f.read(*args)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self._f.close()
        return False

    def __iter__(self):
        return iter(self._f)

    def __getattr__(self, name):
        return getattr(self._f, name)


class FaultInjector:
    """Installable fault plan registry (see module docstring)."""

    def __init__(self):
        self.plans = []
        self._lock = threading.Lock()
        self._installed = False
        self._real_open = None
        self._real_replace = None
        self._real_rename = None
        self._call_targets = []   # (dotted_name, plan) awaiting patch
        self._patched_calls = []  # (owner, attr, original)
        # serving-side plans need ARGUMENT access (which request rides
        # the harvested program, which slot is draining), so they carry
        # their own wrapper factory instead of the blind call patch
        self._custom_targets = []  # (dotted_name, plan, make_patched)
        # process-level plans (ISSUE 16)
        self._wire_hooks = []        # hooks awaiting install
        self._active_wire_hooks = []  # hooks currently registered
        self._paused_pids = set()    # SIGSTOP'd workers owed a SIGCONT

    # -- arming ------------------------------------------------------------

    def fail(self, match, op="write", errno_=_errno.EIO, times=1,
             after_bytes=0, action="raise", marker=None):
        plan = FaultPlan(match, op=op, errno_=errno_, times=times,
                         after_bytes=after_bytes, action=action,
                         marker=marker)
        self.plans.append(plan)
        return plan

    def fail_write(self, match, errno_=_errno.ENOSPC, times=1,
                   after_bytes=0):
        """Nth write to a matching path raises OSError(errno_) after
        ``after_bytes`` bytes actually landed (partial write)."""
        return self.fail(match, op="write", errno_=errno_, times=times,
                         after_bytes=after_bytes)

    def fail_read(self, match, errno_=_errno.EIO, times=1):
        return self.fail(match, op="read", errno_=errno_, times=times)

    def truncate_write(self, match, after_bytes):
        """Silent short write: only ``after_bytes`` land, success is
        reported — detectable only by size/checksum validation."""
        return self.fail(match, op="write", after_bytes=after_bytes,
                         action="truncate")

    def crash(self, match, op="open", after_bytes=0):
        """os._exit(41) when ``op`` touches a matching path."""
        return self.fail(match, op=op, action="crash",
                         after_bytes=after_bytes)

    def pause(self, match, op="open", marker=None):
        """Touch ``marker`` then sleep forever at the matching
        operation so the test harness can SIGKILL this process at an
        exact point."""
        return self.fail(match, op=op, action="pause", marker=marker)

    def preempt(self, match, op="open", times=1):
        """Deliver a real SIGTERM to this process when ``op`` touches a
        matching path, then let the operation proceed — the SIGTERM-
        with-grace-window preemption scenario: an installed
        ``PreemptionGuard`` records the signal and the training loop
        drains at its next step boundary."""
        return self.fail(match, op=op, action="sigterm", times=times)

    def fail_call(self, target, action="raise", errno_=_errno.EIO,
                  times=1, after_calls=0):
        """Arm a fault on a dotted CALLABLE instead of a file path:
        ``target`` names a module-level function or class method (e.g.
        ``"paddle_tpu.distributed.communication.all_reduce"``); the
        plan fires once more than ``after_calls`` invocations have
        happened, then the chosen action runs *before* the original
        callable — ``"crash"`` is a worker killed mid-collective /
        mid-step, ``"raise"`` an injected failure unwinding through
        it, ``"sigterm"`` a preemption notice landing inside it.
        Patched on :meth:`install`, restored on :meth:`uninstall`."""
        plan = FaultPlan(target, op="call", errno_=errno_, times=times,
                         action=action, after_calls=after_calls)
        self.plans.append(plan)
        self._call_targets.append((target, plan))
        if self._installed:
            self._patch_call(target, plan)
        return plan

    def crash_call(self, target, after_calls=0, times=1):
        """``os._exit(41)`` (SIGKILL-equivalent) inside the named
        callable — kill a worker mid-step / mid-collective at an
        exact, randomizable point."""
        return self.fail_call(target, action="crash", times=times,
                              after_calls=after_calls)

    def fires(self):
        """Total number of times any plan fired."""
        return sum(p.fired for p in self.plans)

    # -- serving-side plans (ISSUE 10) -------------------------------------
    # Chaos shapes for the continuous-batching engine: a poisoned
    # request, a slot that stops draining, a page-reclamation leak.
    # Each is a call plan on an engine method whose wrapper inspects
    # the call's arguments, so the fault is attributable (fires only
    # when the chosen request/slot is involved).

    _SERVING = "paddle_tpu.inference.serving.ContinuousBatchingEngine."

    def _custom(self, target, plan, make_patched):
        self._custom_targets.append((target, plan, make_patched))
        if self._installed:
            self._patch_custom(target, plan, make_patched)

    def _claim(self, plan):
        """Claim one firing of ``plan`` if it is still live."""
        with self._lock:
            if plan.fired >= plan.times:
                return False
            plan.fired += 1
            return True

    def poison_request(self, request_id, times=1):
        """Poison-request plan: harvesting a compiled serving step
        RAISES ``FloatingPointError`` (the NaN-sampler-output shape
        materializing at the packed fetch) whenever the chosen request
        rides the harvested program. The engine's containment boundary
        must quarantine the poison and recompute its co-scheduled
        innocents — never die."""
        plan = FaultPlan(f"poison_request:{request_id}", op="call",
                         action="raise", times=times)
        self.plans.append(plan)
        rid = int(request_id)
        injector = self

        def make(original, plan_):
            def patched(eng, rec, *a, **kw):
                snap = rec[1]   # both harvest records carry the
                                # slot->request snapshot at index 1
                if any(r is not None and r.request_id == rid
                       for r in snap) and injector._claim(plan_):
                    raise FloatingPointError(
                        f"fault injected: NaN sampler output "
                        f"(poison request {rid})")
                return original(eng, rec, *a, **kw)
            return patched

        for meth in ("_harvest_step", "_harvest_chunk"):
            self._custom(self._SERVING + meth, plan, make)
        return plan

    def wedge_slot(self, slot, times=1):
        """Wedge-slot plan: the drain pass SKIPS the chosen slot for
        ``times`` passes — the stream sits finished-but-undrained,
        holding its pages (the stuck-slot shape the deadlock-break
        eviction and the EngineSupervisor exist for)."""
        plan = FaultPlan(f"wedge_slot:{slot}", op="call",
                         action="raise", times=times)
        self.plans.append(plan)
        slot_i = int(slot)
        injector = self

        def make(original, plan_):
            def patched(eng, *a, **kw):
                if not (slot_i < eng.num_slots
                        and eng.slot_req[slot_i] is not None
                        and injector._claim(plan_)):
                    return original(eng, *a, **kw)
                # emits-inflight makes the drain defer exactly this
                # slot, without touching any device state
                eng._emits_inflight[slot_i] += 1
                try:
                    return original(eng, *a, **kw)
                finally:
                    eng._emits_inflight[slot_i] -= 1
            return patched

        self._custom(self._SERVING + "_drain", plan, make)
        return plan

    # -- replica-level plans (ISSUE 11) ------------------------------------
    # Fleet chaos shapes: a replica that dies, one that wedges, one
    # that merely straggles. Each matches the engine's
    # ``_fleet_replica_id`` tag (set by ServingFleet — re-applied on
    # every supervised rebuild — or settable by hand on a bare engine),
    # so one plan targets exactly one replica of the shared class.

    def kill_replica(self, replica_id, times=1, after_steps=0):
        """Replica death, supervisor-visible: the chosen replica's
        ``step()`` raises ``RuntimeError`` BEFORE any scheduler work
        runs — the whole turn dies, exactly what a crashed worker
        looks like from the driver. The replica's EngineSupervisor
        salvages + restarts; once its budget is spent the fleet opens
        the circuit breaker and fails the queue over to siblings.
        ``after_steps`` counts only the chosen replica's steps."""
        plan = FaultPlan(f"kill_replica:{replica_id}", op="call",
                         action="raise", times=times,
                         after_calls=after_steps)
        self.plans.append(plan)
        rid = int(replica_id)
        injector = self

        def make(original, plan_):
            def patched(eng, *a, **kw):
                if getattr(eng, "_fleet_replica_id", None) == rid:
                    live = injector._take_call(plan_)
                    if live is not None:
                        raise RuntimeError(
                            f"fault injected: replica {rid} died "
                            f"mid-step")
                return original(eng, *a, **kw)
            return patched

        self._custom(self._SERVING + "step", plan, make)
        return plan

    def wedge_replica(self, replica_id, times=10_000):
        """Wedged replica: ``step()`` returns promptly having done
        NOTHING — the scheduler turn is skipped wholesale, so the
        replica still heartbeats (the step returns; liveness is fine)
        but never makes progress. Must be caught by the fleet's
        NO-PROGRESS health check, not the liveness check, and without
        tripping the engine's true-deadlock stall diagnostic (which
        lives only in ``run()``)."""
        plan = FaultPlan(f"wedge_replica:{replica_id}", op="call",
                         action="raise", times=times)
        self.plans.append(plan)
        rid = int(replica_id)
        injector = self

        def make(original, plan_):
            def patched(eng, *a, **kw):
                if getattr(eng, "_fleet_replica_id", None) == rid \
                        and injector._claim(plan_):
                    return []      # a turn that does nothing
                return original(eng, *a, **kw)
            return patched

        self._custom(self._SERVING + "step", plan, make)
        return plan

    def slow_replica(self, replica_id, delay_s=0.05, stride=4,
                     times=10_000):
        """Straggler replica: inflated step latency — every matching
        ``step()`` burns ``delay_s`` of wall clock, and only every
        ``stride``-th actually advances the scheduler (in the fleet's
        cooperative round-robin a slow worker completes fewer turns
        per unit time; this models that without threads). Progress
        continues — just slowly — so the no-progress health check must
        NOT fire; hedged dispatch is what this shape exercises."""
        plan = FaultPlan(f"slow_replica:{replica_id}", op="call",
                         action="raise", times=times)
        self.plans.append(plan)
        rid = int(replica_id)
        delay = float(delay_s)
        stride_n = max(1, int(stride))
        injector = self

        def make(original, plan_):
            def patched(eng, *a, **kw):
                if getattr(eng, "_fleet_replica_id", None) == rid \
                        and injector._claim(plan_):
                    time.sleep(delay)
                    if plan_.fired % stride_n:
                        return []  # the slice elapsed, no turn ran
                return original(eng, *a, **kw)
            return patched

        self._custom(self._SERVING + "step", plan, make)
        return plan

    def leak_pages(self, n=1, times=1):
        """Page-leak plan: the engine's page-reclamation path silently
        DROPS the first ``n`` pages it would have returned to the pool
        — the reclamation-bug shape the PADDLE_TPU_SERVING_AUDIT
        invariant exists to catch loudly."""
        plan = FaultPlan("leak_pages", op="call", action="raise",
                         times=times)
        self.plans.append(plan)
        n_drop = int(n)
        injector = self

        def make(original, plan_):
            def patched(eng, pages, *a, **kw):
                if pages and injector._claim(plan_):
                    pages = list(pages)[n_drop:]
                return original(eng, pages, *a, **kw)
            return patched

        self._custom(self._SERVING + "_release_pages", plan, make)
        return plan

    # -- process-level plans (ISSUE 16) ------------------------------------
    # Real-process fault shapes for ProcReplica workers: a worker
    # killed with an actual SIGKILL, one frozen with SIGSTOP, and a
    # lossy wire (dropped / delayed / corrupted frames) injected at
    # the parent transport's fault-hook seam. Matched by replica id
    # like the replica-level plans above.

    _PROC = "paddle_tpu.inference.proc_replica.ProcReplica."

    def kill_worker(self, replica_id, times=1, after_steps=0):
        """Real worker death: deliver an actual SIGKILL to the chosen
        replica's worker process right before a matching step RPC —
        the parent sees waitpid/EOF, salvages from its parent-side
        shadow, and respawns under the restart budget (past it, the
        breaker opens). ``after_steps`` counts only the chosen
        replica's step RPCs."""
        plan = FaultPlan(f"kill_worker:{replica_id}", op="call",
                         action="raise", times=times,
                         after_calls=after_steps)
        self.plans.append(plan)
        rid = int(replica_id)
        injector = self

        def make(original, plan_):
            def patched(rep, *a, **kw):
                if rep.id == rid:
                    live = injector._take_call(plan_)
                    if live is not None and rep.worker_pid:
                        try:
                            os.kill(rep.worker_pid, _signal.SIGKILL)
                        except (ProcessLookupError, OSError):
                            pass
                return original(rep, *a, **kw)
            return patched

        self._custom(self._PROC + "_step_rpc", plan, make)
        return plan

    def pause_worker(self, replica_id, times=1, after_steps=0):
        """Hung worker: SIGSTOP the chosen replica's worker process.
        Heartbeats stop but the process is NOT dead, so the parent
        must classify it as hung via heartbeat timeout (SIGTERM with
        grace, then SIGKILL; wedge ejection — never the breaker). Any
        pid still stopped gets a SIGCONT on :meth:`uninstall` so
        nothing outlives the test."""
        plan = FaultPlan(f"pause_worker:{replica_id}", op="call",
                         action="raise", times=times,
                         after_calls=after_steps)
        self.plans.append(plan)
        rid = int(replica_id)
        injector = self

        def make(original, plan_):
            def patched(rep, *a, **kw):
                if rep.id == rid:
                    live = injector._take_call(plan_)
                    if live is not None and rep.worker_pid:
                        try:
                            os.kill(rep.worker_pid, _signal.SIGSTOP)
                            injector._paused_pids.add(rep.worker_pid)
                        except (ProcessLookupError, OSError):
                            pass
                return original(rep, *a, **kw)
            return patched

        self._custom(self._PROC + "_step_rpc", plan, make)
        return plan

    def _add_wire_hook(self, hook):
        from paddle_tpu.inference import wire as _wire
        _wire.add_fault_hook(hook)
        self._active_wire_hooks.append(hook)

    def _wire_plan(self, kind, replica_id, times, direction,
                   after_frames, act):
        if direction not in ("rx", "tx"):
            raise ValueError(f"unknown wire direction {direction!r}")
        plan = FaultPlan(f"{kind}:{replica_id}", op="call",
                         action="raise", times=times,
                         after_calls=after_frames)
        self.plans.append(plan)
        rid = int(replica_id)
        injector = self

        def hook(hook_rid, hook_dir, data):
            if hook_rid != rid or hook_dir != direction:
                return data
            live = injector._take_call(plan)
            if live is None:
                return data
            return act(data)

        self._wire_hooks.append(hook)
        if self._installed:
            self._add_wire_hook(hook)
        return plan

    def drop_frame(self, replica_id, times=1, direction="rx",
                   after_frames=0):
        """Lossy wire: the matching transport chunk vanishes — a sent
        frame never leaves (``direction="tx"``) or a received chunk
        never arrives (``"rx"``). The RPC layer's deadline + bounded
        retransmit must absorb it; the worker's reply cache keeps the
        retransmit exactly-once."""
        return self._wire_plan("drop_frame", replica_id, times,
                               direction, after_frames,
                               lambda data: None)

    def delay_frame(self, replica_id, delay_s=0.05, times=1,
                    direction="rx", after_frames=0):
        """Slow wire: the matching chunk is held for ``delay_s``
        before delivery — exercises the RPC deadline/backoff path
        without losing any bytes."""
        delay = float(delay_s)

        def act(data):
            time.sleep(delay)
            return data

        return self._wire_plan("delay_frame", replica_id, times,
                               direction, after_frames, act)

    def corrupt_frame(self, replica_id, times=1, direction="rx",
                      after_frames=0):
        """Corrupt wire: one byte in the middle of the matching chunk
        is bit-flipped — the decoder must surface a typed
        ``WireError`` (bad magic / CRC mismatch), resync, and the RPC
        layer must retransmit; never a hang, never a half-applied
        message."""
        def act(data):
            if not data:
                return data
            buf = bytearray(data)
            buf[len(buf) // 2] ^= 0xFF
            return bytes(buf)

        return self._wire_plan("corrupt_frame", replica_id, times,
                               direction, after_frames, act)

    # -- plan matching / actions -------------------------------------------

    def _take(self, path, op, pending=None):
        """Claim the first live plan matching (path, op); for writes,
        only once the byte threshold is actually reached."""
        with self._lock:
            for plan in self.plans:
                if plan.fired >= plan.times or plan.op != op:
                    continue
                if plan.match not in path:
                    continue
                if (op == "write" and pending is not None
                        and pending <= plan.after_bytes):
                    continue  # threshold not reached yet this write
                plan.fired += 1
                return plan
        return None

    def _act(self, plan, path):
        if plan.action == "crash":
            os._exit(41)
        if plan.action == "sigterm":
            # real signal to self; the caller PROCEEDS with the
            # operation — preemption is asynchronous by nature
            os.kill(os.getpid(), _signal.SIGTERM)
            return
        if plan.action == "pause":
            if plan.marker:
                with self._real_open(plan.marker, "w") as m:
                    m.write(path)
            while True:
                time.sleep(60)
        raise OSError(plan.errno,
                      f"fault injected ({plan.op} -> {plan.action})", path)

    def _take_call(self, plan):
        """Claim a call plan: fires once the invocation count passes
        ``after_calls`` (counted across install lifetime)."""
        with self._lock:
            plan.calls += 1
            if plan.fired >= plan.times:
                return None
            if plan.calls <= plan.after_calls:
                return None
            plan.fired += 1
            return plan

    # -- patching ----------------------------------------------------------

    @staticmethod
    def _resolve_owner(dotted):
        """(owner, attr) for a dotted target: the longest importable
        module prefix, then a getattr chain (supports Class.method)."""
        parts = dotted.split(".")
        mod = None
        rest = None
        for i in range(len(parts) - 1, 0, -1):
            try:
                mod = importlib.import_module(".".join(parts[:i]))
                rest = parts[i:]
                break
            except ImportError:
                continue
        if mod is None or not rest:
            raise ValueError(f"cannot resolve fault target {dotted!r}")
        owner = mod
        for p in rest[:-1]:
            owner = getattr(owner, p)
        if not hasattr(owner, rest[-1]):
            raise ValueError(
                f"fault target {dotted!r}: {owner!r} has no "
                f"attribute {rest[-1]!r}")
        return owner, rest[-1]

    def _patch_call(self, target, plan):
        injector = self

        def make(original, plan_):
            def patched(*a, **kw):
                live = injector._take_call(plan_)
                if live is not None:
                    injector._act(live, target)  # crash/raise/sigterm
                return original(*a, **kw)
            return patched

        self._patch_custom(target, plan, make)

    def _patch_custom(self, target, plan, make_patched):
        """The one patch/restore skeleton every call plan rides —
        blind plans (_patch_call) and argument-aware serving plans
        alike, so install/uninstall bookkeeping lives in one place."""
        owner, attr = self._resolve_owner(target)
        original = getattr(owner, attr)
        patched = make_patched(original, plan)
        patched.__name__ = getattr(original, "__name__", attr)
        setattr(owner, attr, patched)
        self._patched_calls.append((owner, attr, original))

    def _open(self, file, mode="r", *args, **kwargs):
        path = None
        if isinstance(file, (str, bytes, os.PathLike)):
            path = os.fsdecode(os.fspath(file))
        if path is not None:
            plan = self._take(path, "open")
            if plan is not None:
                self._act(plan, path)
        f = self._real_open(file, mode, *args, **kwargs)
        if path is not None and any(
                p.op in ("write", "read") and p.fired < p.times
                and p.match in path for p in self.plans):
            return _FaultFile(f, path, self)
        return f

    def _rename_like(self, real):
        def patched(src, dst, **kwargs):
            for p in (os.fspath(src), os.fspath(dst)):
                sp = os.fsdecode(p) if isinstance(p, bytes) else str(p)
                plan = self._take(sp, "rename")
                if plan is not None:
                    self._act(plan, sp)
            return real(src, dst, **kwargs)
        return patched

    def install(self):
        if self._installed:
            return self
        self._real_open = builtins.open
        self._real_replace = os.replace
        self._real_rename = os.rename
        builtins.open = self._open
        os.replace = self._rename_like(self._real_replace)
        os.rename = self._rename_like(self._real_rename)
        self._installed = True
        for target, plan in self._call_targets:
            self._patch_call(target, plan)
        for target, plan, make in self._custom_targets:
            self._patch_custom(target, plan, make)
        for hook in self._wire_hooks:
            if hook not in self._active_wire_hooks:
                self._add_wire_hook(hook)
        return self

    def uninstall(self):
        if not self._installed:
            return
        builtins.open = self._real_open
        os.replace = self._real_replace
        os.rename = self._real_rename
        while self._patched_calls:
            owner, attr, original = self._patched_calls.pop()
            setattr(owner, attr, original)
        if self._active_wire_hooks:
            from paddle_tpu.inference import wire as _wire
            while self._active_wire_hooks:
                _wire.remove_fault_hook(self._active_wire_hooks.pop())
        while self._paused_pids:
            pid = self._paused_pids.pop()
            try:
                os.kill(pid, _signal.SIGCONT)
            except (ProcessLookupError, OSError):
                pass
        self._installed = False

    def __enter__(self):
        return self.install()

    def __exit__(self, *exc):
        self.uninstall()
        return False
