"""Deterministic filesystem fault injection.

Robustness claims ("a save killed mid-write never yields a loadable
checkpoint") are only worth anything if a test can *produce* the fault
on demand. ``FaultInjector`` patches ``builtins.open`` (which numpy's
``np.save``/``np.load`` also route through) plus ``os.replace`` /
``os.rename``, and fires registered :class:`FaultPlan`\\ s when an
operation touches a matching path:

- ``action="raise"`` — raise ``OSError(errno)`` (ENOSPC, EIO, ...),
  optionally after ``after_bytes`` of a write landed (a partial write
  followed by the error, the torn-write shape).
- ``action="truncate"`` — write only ``after_bytes`` bytes but report
  full success: the silent short write that only checksums catch.
- ``action="crash"`` — ``os._exit(41)``: abrupt process death at an
  exact operation, indistinguishable from SIGKILL to an observer (no
  atexit, no buffer flush, no cleanup).
- ``action="pause"`` — touch ``marker`` then sleep forever, so a
  parent test process can deliver a *real* SIGKILL at a known point
  (e.g. between shard write and commit).

Plans match by substring of the path and fire deterministically: each
plan fires at most ``times`` times, in registration order. Use as a
context manager so ``builtins.open`` is always restored::

    with FaultInjector() as fi:
        fi.fail("w.r0.s0.npy", op="write", errno_=errno.ENOSPC)
        save_state_dict(sd, path)     # first write ENOSPCs, retry wins
        assert fi.fires() == 1
"""

from __future__ import annotations

import builtins
import errno as _errno
import os
import threading
import time

__all__ = ["FaultInjector", "FaultPlan"]


class FaultPlan:
    """One armed fault: fires when ``op`` touches a path containing
    ``match``, at most ``times`` times."""

    def __init__(self, match, op="write", errno_=_errno.EIO, times=1,
                 after_bytes=0, action="raise", marker=None):
        if op not in ("open", "write", "read", "rename"):
            raise ValueError(f"unknown fault op {op!r}")
        if action not in ("raise", "truncate", "crash", "pause"):
            raise ValueError(f"unknown fault action {action!r}")
        self.match = match
        self.op = op
        self.errno = errno_
        self.times = int(times)
        self.after_bytes = int(after_bytes)
        self.action = action
        self.marker = marker
        self.fired = 0

    def __repr__(self):
        return (f"FaultPlan({self.match!r}, op={self.op}, "
                f"action={self.action}, fired={self.fired}/{self.times})")


class _FaultFile:
    """File proxy that consults the injector on write()/read()."""

    def __init__(self, f, path, injector):
        self._f = f
        self._path = path
        self._inj = injector
        self._written = 0
        self._truncated = False

    def write(self, data):
        if self._truncated:
            return len(data)  # silently dropped tail of a short write
        plan = self._inj._take(self._path, "write",
                               pending=self._written + len(data))
        if plan is not None:
            keep = max(0, plan.after_bytes - self._written)
            if keep:
                self._f.write(data[:keep])
                self._written += keep
            if plan.action == "truncate":
                self._truncated = True
                return len(data)  # lie: report full success
            self._inj._act(plan, self._path)  # raise / crash / pause
        n = self._f.write(data)
        self._written += len(data)
        return n

    def read(self, *args):
        plan = self._inj._take(self._path, "read")
        if plan is not None:
            self._inj._act(plan, self._path)
        return self._f.read(*args)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self._f.close()
        return False

    def __iter__(self):
        return iter(self._f)

    def __getattr__(self, name):
        return getattr(self._f, name)


class FaultInjector:
    """Installable fault plan registry (see module docstring)."""

    def __init__(self):
        self.plans = []
        self._lock = threading.Lock()
        self._installed = False
        self._real_open = None
        self._real_replace = None
        self._real_rename = None

    # -- arming ------------------------------------------------------------

    def fail(self, match, op="write", errno_=_errno.EIO, times=1,
             after_bytes=0, action="raise", marker=None):
        plan = FaultPlan(match, op=op, errno_=errno_, times=times,
                         after_bytes=after_bytes, action=action,
                         marker=marker)
        self.plans.append(plan)
        return plan

    def fail_write(self, match, errno_=_errno.ENOSPC, times=1,
                   after_bytes=0):
        """Nth write to a matching path raises OSError(errno_) after
        ``after_bytes`` bytes actually landed (partial write)."""
        return self.fail(match, op="write", errno_=errno_, times=times,
                         after_bytes=after_bytes)

    def fail_read(self, match, errno_=_errno.EIO, times=1):
        return self.fail(match, op="read", errno_=errno_, times=times)

    def truncate_write(self, match, after_bytes):
        """Silent short write: only ``after_bytes`` land, success is
        reported — detectable only by size/checksum validation."""
        return self.fail(match, op="write", after_bytes=after_bytes,
                         action="truncate")

    def crash(self, match, op="open", after_bytes=0):
        """os._exit(41) when ``op`` touches a matching path."""
        return self.fail(match, op=op, action="crash",
                         after_bytes=after_bytes)

    def pause(self, match, op="open", marker=None):
        """Touch ``marker`` then sleep forever at the matching
        operation so the test harness can SIGKILL this process at an
        exact point."""
        return self.fail(match, op=op, action="pause", marker=marker)

    def fires(self):
        """Total number of times any plan fired."""
        return sum(p.fired for p in self.plans)

    # -- plan matching / actions -------------------------------------------

    def _take(self, path, op, pending=None):
        """Claim the first live plan matching (path, op); for writes,
        only once the byte threshold is actually reached."""
        with self._lock:
            for plan in self.plans:
                if plan.fired >= plan.times or plan.op != op:
                    continue
                if plan.match not in path:
                    continue
                if (op == "write" and pending is not None
                        and pending <= plan.after_bytes):
                    continue  # threshold not reached yet this write
                plan.fired += 1
                return plan
        return None

    def _act(self, plan, path):
        if plan.action == "crash":
            os._exit(41)
        if plan.action == "pause":
            if plan.marker:
                with self._real_open(plan.marker, "w") as m:
                    m.write(path)
            while True:
                time.sleep(60)
        raise OSError(plan.errno,
                      f"fault injected ({plan.op} -> {plan.action})", path)

    # -- patching ----------------------------------------------------------

    def _open(self, file, mode="r", *args, **kwargs):
        path = None
        if isinstance(file, (str, bytes, os.PathLike)):
            path = os.fsdecode(os.fspath(file))
        if path is not None:
            plan = self._take(path, "open")
            if plan is not None:
                self._act(plan, path)
        f = self._real_open(file, mode, *args, **kwargs)
        if path is not None and any(
                p.op in ("write", "read") and p.fired < p.times
                and p.match in path for p in self.plans):
            return _FaultFile(f, path, self)
        return f

    def _rename_like(self, real):
        def patched(src, dst, **kwargs):
            for p in (os.fspath(src), os.fspath(dst)):
                sp = os.fsdecode(p) if isinstance(p, bytes) else str(p)
                plan = self._take(sp, "rename")
                if plan is not None:
                    self._act(plan, sp)
            return real(src, dst, **kwargs)
        return patched

    def install(self):
        if self._installed:
            return self
        self._real_open = builtins.open
        self._real_replace = os.replace
        self._real_rename = os.rename
        builtins.open = self._open
        os.replace = self._rename_like(self._real_replace)
        os.rename = self._rename_like(self._real_rename)
        self._installed = True
        return self

    def uninstall(self):
        if not self._installed:
            return
        builtins.open = self._real_open
        os.replace = self._real_replace
        os.rename = self._real_rename
        self._installed = False

    def __enter__(self):
        return self.install()

    def __exit__(self, *exc):
        self.uninstall()
        return False
