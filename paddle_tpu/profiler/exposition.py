"""Live observability exposition — the operational front door (ISSUE 13).

PR 9 gave the process a metrics registry with FILE exposition
(``registry.export(path)``); a fleet serving live traffic needs a
scrape endpoint. :class:`ObservabilityServer` is a stdlib-only
``http.server`` running in a daemon thread, serving:

- ``/metrics`` — Prometheus text exposition v0.0.4 rendered from the
  configured registry (a :class:`~.metrics.FederatedRegistry` when a
  ServingFleet wires it: per-replica labeled children + summed
  totals);
- ``/statusz`` — one JSON document assembled from named SECTION
  PROVIDERS (replica health/breaker states, prefix-cache hit rates,
  goodput summary, flight-recorder incidents, SLO attainment/alerts,
  the N slowest recent request traces). Each provider is guarded: a
  section that raises mid-churn (a replica being torn down under the
  scrape) degrades to an ``{"error": ...}`` stanza — the scrape always
  parses;
- ``/healthz`` — liveness (200 ``ok``).

Scrape-safety contract (the chaos gate pins it):

- the handler READS; nothing in it writes runtime state, takes engine
  locks, or touches the device — the serving hot loop is never blocked
  by a scrape;
- every response is fully materialized before a byte is sent
  (Content-Length framing, no streaming) — a scraper never reads a
  torn document, the same invariant the atomic file exports hold;
- handler exceptions return a 500 with a JSON body, never a dropped
  connection mid-document.

``port=0`` binds an ephemeral port (tests); ``server.port`` reports
the bound port. Scrapes are themselves metered (``obs/scrapes`` /
``obs/scrape_errors`` on the process-wide registry) so the
observability plane's own traffic stays observable.
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from . import metrics as _metrics
# the response skeleton is SHARED with the API front door
# (inference/api_server.py) via httpbase so the two servers cannot
# drift on torn-response / Content-Length behavior; evaluate_sections
# is re-exported from its historical home here
from .httpbase import evaluate_sections, materialize_response

__all__ = ["ObservabilityServer", "evaluate_sections"]

_metrics.declare("obs/scrapes", "counter",
                 "HTTP scrapes served by the ObservabilityServer "
                 "(/metrics + /statusz + /healthz)")
_metrics.declare("obs/scrape_errors", "counter",
                 "ObservabilityServer requests that returned a 500 "
                 "(a section provider or the registry render raised)")


class _Handler(BaseHTTPRequestHandler):
    """One scrape. The server instance hangs off ``self.server.owner``
    (the ObservabilityServer)."""

    protocol_version = "HTTP/1.1"

    # silence the default stderr access log (scrapes arrive every few
    # seconds forever; the serving process's stderr is for the runtime)
    def log_message(self, fmt, *args):  # noqa: A002
        pass

    def _send(self, code, body, ctype):
        code, headers, data = materialize_response(code, body, ctype)
        self.send_response(code)
        for name, value in headers:
            self.send_header(name, value)
        self.end_headers()
        self.wfile.write(data)

    def do_GET(self):  # noqa: N802 — http.server API
        owner = self.server.owner
        path = self.path.split("?", 1)[0]
        _metrics.get_registry().counter("obs/scrapes").inc()
        try:
            if path == "/healthz":
                self._send(200, "ok\n", "text/plain; charset=utf-8")
            elif path == "/metrics":
                self._send(200, owner.render_metrics(),
                           "text/plain; version=0.0.4; charset=utf-8")
            elif path == "/statusz":
                self._send(200, owner.render_statusz(),
                           "application/json")
            else:
                self._send(404, json.dumps(
                    {"error": f"unknown path {path!r}",
                     "paths": ["/metrics", "/statusz", "/healthz"]}),
                    "application/json")
        except Exception as exc:  # noqa: BLE001 — a scrape must never
            # kill the handler thread or drop mid-document
            _metrics.get_registry().counter("obs/scrape_errors").inc()
            try:
                self._send(500, json.dumps(
                    {"error": f"{type(exc).__name__}: {exc}"}),
                    "application/json")
            except OSError:
                pass


class ObservabilityServer:
    """Background-thread HTTP exposition of a metrics registry plus
    named /statusz sections (module docstring).

    ``registry`` defaults to the process-wide registry; a fleet passes
    its :class:`~.metrics.FederatedRegistry`. ``sections`` maps section
    name -> zero-arg callable returning a JSON-serializable value,
    evaluated per scrape (live state, not a cached copy);
    :meth:`add_section` registers more after construction.
    """

    def __init__(self, registry=None, sections=None, host="127.0.0.1",
                 port=0, pre_scrape=None):
        self.registry = registry
        #: zero-arg callable run before every /metrics render (best-
        #: effort): the fleet wires the SLO tracker's refresh() here
        #: so a Prometheus-only scraper reads current burn/attainment
        #: gauges, not values frozen since the last recorded request
        self.pre_scrape = pre_scrape
        self._sections = dict(sections or {})
        self._lock = threading.Lock()
        self._httpd = ThreadingHTTPServer((host, int(port)), _Handler)
        self._httpd.daemon_threads = True
        self._httpd.owner = self
        self._thread = None

    # -- lifecycle ---------------------------------------------------------

    @property
    def port(self) -> int:
        return self._httpd.server_address[1]

    @property
    def url(self) -> str:
        host, port = self._httpd.server_address[:2]
        return f"http://{host}:{port}"

    def start(self):
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._httpd.serve_forever,
                kwargs={"poll_interval": 0.05},
                name="obs-exposition", daemon=True)
            self._thread.start()
        return self

    def stop(self):
        if self._thread is not None:
            self._httpd.shutdown()
            self._thread.join(timeout=5.0)
            self._thread = None
        self._httpd.server_close()

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()
        return False

    # -- sections ----------------------------------------------------------

    def add_section(self, name, provider):
        """Register/replace a /statusz section provider (zero-arg
        callable -> JSON-serializable)."""
        with self._lock:
            self._sections[str(name)] = provider
        return self

    # -- renders (also the test surface: no HTTP needed) --------------------

    def render_metrics(self) -> str:
        if self.pre_scrape is not None:
            try:
                self.pre_scrape()
            except Exception:  # noqa: BLE001 — a refresh hook must
                pass           # never fail the scrape itself
        reg = self.registry or _metrics.get_registry()
        return reg.export_prometheus()

    def render_statusz(self) -> str:
        """The /statusz JSON document (see :func:`evaluate_sections`
        for the guarded evaluation contract)."""
        with self._lock:
            sections = dict(self._sections)
        doc = evaluate_sections(sections)
        # default=str: a section that leaks a non-JSON value (numpy
        # scalar, Exception) must not make the whole document
        # unserializable mid-incident — exactly when /statusz matters
        return json.dumps(doc, default=str, sort_keys=True)
