"""Typed metrics registry — the production observability substrate.

Every quantitative claim the runtime makes about itself (serving
latency percentiles, fit pipeline gauges, elastic restart accounting,
flight-recorder health) flows through ONE registry of typed metrics:

- :class:`Counter` — monotonically increasing totals (``inc``), float
  or int, exact under concurrent increment (per-metric lock; the
  prefetcher and scheduler threads bump counters concurrently).
- :class:`Gauge` — last-written point-in-time value (``set``).
- :class:`Histogram` — streaming distribution with a BOUNDED
  reservoir (Vitter's algorithm R): ``observe()`` is O(1), memory is
  fixed at ``capacity`` samples forever, and ``percentile(q)`` stays
  statistically faithful over millions of observations. This replaces
  the unbounded per-request latency sample lists the serving engine
  used to grow (``_ttft_ms``/``_itl_ms``, serving.py) — a long-lived
  engine's memory now stays flat.

Naming is enforced: every metric is ``subsystem/name``
(``serving/tokens_emitted``, ``hapi/input_wait_ms``, ``obs/overhead_frac``)
— ``tools/check_metric_names.py`` lints the convention and that every
registered name is documented in docs/observability.md.

Export surfaces (both atomic — tmp + fsync + rename, the checkpoint
invariant, so a scraper or post-mortem never reads a torn file):

- ``registry.snapshot()`` → plain dict (JSON-ready; the flight
  recorder embeds it in crash bundles);
- ``registry.export(path)`` → Prometheus text exposition v0.0.4
  (counters/gauges as-is, histograms as summaries with quantile
  labels);
- ``registry.export_json(path)`` → the snapshot, atomically.

Two registry scopes exist: the process-wide default
(:func:`get_registry` — hapi fit, elastic/restart counters, jit
compile accounting) whose updates MIRROR into the structured tracer
when tracing is enabled (so chrome-trace exports keep carrying the
gauges, exactly as before this registry existed), and per-component
instances (each ``ContinuousBatchingEngine`` owns one, so two engines
in one process never cross-pollute and ``gauges()`` stays
per-engine).

Deliberately stdlib-only (no jax): imported from hot paths.
"""

from __future__ import annotations

import random
import re
import threading
import zlib

__all__ = ["Counter", "Gauge", "Histogram", "MetricsRegistry",
           "FederatedRegistry", "get_registry", "declare", "catalog",
           "catalog_markdown", "METRIC_NAME_RE"]

#: the ``subsystem/name`` convention, linted by
#: tools/check_metric_names.py
METRIC_NAME_RE = re.compile(r"^[a-z][a-z0-9_]*/[a-z][a-z0-9_]*$")

#: process-wide name -> (kind, help) vocabulary. Every registration in
#: ANY registry lands here (metric NAMES are a global vocabulary even
#: when their values are per-component); :func:`declare` populates it
#: at import time so the docs table and the lint gate can see names
#: before any component is constructed.
_CATALOG: dict[str, tuple[str, str]] = {}
_CATALOG_LOCK = threading.Lock()


def _check_name(name: str) -> str:
    if not METRIC_NAME_RE.match(name):
        raise ValueError(
            f"metric name {name!r} violates the subsystem/name "
            "convention (lowercase [a-z0-9_], exactly one '/'); see "
            "docs/observability.md")
    return name


def declare(name: str, kind: str, help: str) -> str:  # noqa: A002
    """Register ``name`` in the process-wide metric catalog without
    creating a metric. Modules declare their vocabulary at import time
    (literal arguments — ``tools/check_metric_names.py`` parses these
    statically); the registry pulls help text from here when a metric
    is later instantiated."""
    _check_name(name)
    if kind not in ("counter", "gauge", "histogram"):
        raise ValueError(f"unknown metric kind {kind!r}")
    with _CATALOG_LOCK:
        prev = _CATALOG.get(name)
        if prev is not None and prev[0] != kind:
            raise ValueError(
                f"metric {name!r} re-declared as {kind} (was {prev[0]})")
        _CATALOG[name] = (kind, help)
    return name


def catalog() -> dict[str, tuple[str, str]]:
    """A copy of the process-wide name -> (kind, help) catalog."""
    with _CATALOG_LOCK:
        return dict(_CATALOG)


def catalog_markdown() -> str:
    """The docs/observability.md metric table, generated from the
    catalog (one row per declared metric, sorted)."""
    lines = ["| metric | kind | meaning |", "|---|---|---|"]
    for name in sorted(catalog()):
        kind, help_ = _CATALOG[name]
        lines.append(f"| `{name}` | {kind} | {help_} |")
    return "\n".join(lines)


def _mirror_to_trace(name, value, **args):
    """Mirror a counter/gauge update into the structured tracer (one
    enabled-check; zero cost while tracing is off). Keeps chrome-trace
    exports carrying the same gauge streams they did before the
    registry existed."""
    from .trace import get_tracer
    tr = get_tracer()
    if tr.enabled:
        tr.counter(name, value, **args)


class _Metric:
    """Shared base: name, help, per-metric lock, label children."""

    kind = "?"

    def __init__(self, name, help="", mirror=False):  # noqa: A002
        self.name = _check_name(name)
        self.help = help
        self._mirror = bool(mirror)
        self._lock = threading.Lock()
        self._children: dict[tuple, _Metric] = {}
        with _CATALOG_LOCK:
            prev = _CATALOG.get(name)
            if prev is not None and prev[0] != self.kind:
                raise ValueError(
                    f"metric {name!r} registered as {self.kind} but "
                    f"declared as {prev[0]}")
            if prev is None or (help and not prev[1]):
                _CATALOG[name] = (self.kind, help or
                                  (prev[1] if prev else ""))
            elif not help:
                self.help = prev[1]

    def labels(self, **kv):
        """The child metric for a label set (Prometheus idiom):
        ``reg.counter("serving/requests").labels(outcome="eos").inc()``.
        Children share the parent's config and appear in snapshots as
        ``name{k="v"}``."""
        key = tuple(sorted(kv.items()))
        with self._lock:
            child = self._children.get(key)
            if child is None:
                child = type(self)(self.name, self.help,
                                   mirror=self._mirror,
                                   **self._child_kwargs())
                child._label_kv = key
                self._children[key] = child
            return child

    def _child_kwargs(self):
        return {}

    def _label_suffix(self):
        kv = getattr(self, "_label_kv", ())
        if not kv:
            return ""
        inner = ",".join(f'{k}="{v}"' for k, v in kv)
        return "{" + inner + "}"

    def _iter_series(self):
        """(label_suffix, metric) for self + every labeled child."""
        yield self._label_suffix(), self
        with self._lock:
            children = list(self._children.values())
        for c in children:
            yield c._label_suffix(), c


class Counter(_Metric):
    """Monotonic total. ``inc`` is exact under concurrent callers."""

    kind = "counter"

    def __init__(self, name, help="", mirror=False):  # noqa: A002
        super().__init__(name, help, mirror=mirror)
        self._value = 0

    def inc(self, n=1, **args):
        with self._lock:
            self._value += n
            v = self._value
        if self._mirror:
            _mirror_to_trace(self.name, v, **args)
        return v

    def set(self, v, **args):
        """Direct assignment — reset (``reset_gauges``) and restored
        state (ledger reload) only; normal accounting uses ``inc``."""
        with self._lock:
            self._value = v
        if self._mirror:
            _mirror_to_trace(self.name, v, **args)

    @property
    def value(self):
        with self._lock:
            return self._value


class Gauge(_Metric):
    """Point-in-time value; last write wins."""

    kind = "gauge"

    def __init__(self, name, help="", mirror=False):  # noqa: A002
        super().__init__(name, help, mirror=mirror)
        self._value = 0.0

    def set(self, v, **args):
        with self._lock:
            self._value = v
        if self._mirror:
            _mirror_to_trace(self.name, v, **args)

    def inc(self, n=1, **args):
        with self._lock:
            self._value += n
            v = self._value
        if self._mirror:
            _mirror_to_trace(self.name, v, **args)
        return v

    @property
    def value(self):
        with self._lock:
            return self._value


class Histogram(_Metric):
    """Streaming distribution over a BOUNDED reservoir (Vitter's
    algorithm R): after ``capacity`` samples, each new observation
    replaces a uniformly-random slot with probability capacity/count —
    the reservoir stays a uniform sample of the whole stream, memory
    stays fixed, and percentiles stay faithful. count/sum/min/max are
    exact (not sampled). Deterministically seeded per instance so
    tests are reproducible."""

    kind = "histogram"

    def __init__(self, name, help="", mirror=False,  # noqa: A002
                 capacity=1024):
        super().__init__(name, help, mirror=mirror)
        self.capacity = int(capacity)
        if self.capacity < 1:
            raise ValueError("histogram capacity must be >= 1")
        # crc32, not hash(): PYTHONHASHSEED must not change which
        # reservoir slots a replayed stream evicts
        self._rng = random.Random(0xA5F00D ^ zlib.crc32(name.encode()))
        self._samples: list[float] = []
        self.count = 0
        self.sum = 0.0
        self.min = None
        self.max = None

    def _child_kwargs(self):
        return {"capacity": self.capacity}

    def observe(self, v, **_args):
        v = float(v)
        with self._lock:
            self.count += 1
            self.sum += v
            if self.min is None or v < self.min:
                self.min = v
            if self.max is None or v > self.max:
                self.max = v
            if len(self._samples) < self.capacity:
                self._samples.append(v)
            else:
                j = self._rng.randrange(self.count)
                if j < self.capacity:
                    self._samples[j] = v

    def percentile(self, q):
        """q in [0, 100]; 0.0 when empty (the legacy gauge contract)."""
        with self._lock:
            if not self._samples:
                return 0.0
            xs = sorted(self._samples)
        if len(xs) == 1:
            return xs[0]
        # linear interpolation (numpy default) without importing numpy
        pos = (len(xs) - 1) * (q / 100.0)
        lo = int(pos)
        hi = min(lo + 1, len(xs) - 1)
        frac = pos - lo
        return xs[lo] * (1.0 - frac) + xs[hi] * frac

    def reset(self):
        with self._lock:
            self._samples = []
            self.count = 0
            self.sum = 0.0
            self.min = None
            self.max = None

    @property
    def sample_count(self):
        """Resident reservoir size — bounded by ``capacity`` forever
        (the memory-flat regression tests pin this)."""
        with self._lock:
            return len(self._samples)

    def samples(self):
        """A copy of the resident reservoir (federation merges the
        fleet's per-replica reservoirs from these)."""
        with self._lock:
            return list(self._samples)

    def to_dict(self):
        with self._lock:
            n = self.count
            s = self.sum
            mn, mx = self.min, self.max
        return {"count": n, "sum": round(s, 6),
                "min": mn, "max": mx,
                "p50": round(self.percentile(50), 6),
                "p90": round(self.percentile(90), 6),
                "p99": round(self.percentile(99), 6)}


class MetricsRegistry:
    """Get-or-create home for typed metrics (see module docstring).
    ``mirror=True`` (the process-wide default registry) echoes every
    counter/gauge update into the structured tracer while tracing is
    enabled."""

    def __init__(self, mirror=False):
        self._metrics: dict[str, _Metric] = {}
        self._lock = threading.Lock()
        self._mirror = bool(mirror)

    def _get_or_create(self, cls, name, help, **kw):  # noqa: A002
        with self._lock:
            m = self._metrics.get(name)
            if m is not None:
                if not isinstance(m, cls):
                    raise ValueError(
                        f"metric {name!r} already registered as "
                        f"{m.kind}, not {cls.kind}")
                return m
            m = cls(name, help, mirror=self._mirror, **kw)
            self._metrics[name] = m
            return m

    def counter(self, name, help="") -> Counter:  # noqa: A002
        return self._get_or_create(Counter, name, help)

    def gauge(self, name, help="") -> Gauge:  # noqa: A002
        return self._get_or_create(Gauge, name, help)

    def histogram(self, name, help="",  # noqa: A002
                  capacity=1024) -> Histogram:
        return self._get_or_create(Histogram, name, help,
                                   capacity=capacity)

    def get(self, name):
        with self._lock:
            return self._metrics.get(name)

    def names(self):
        with self._lock:
            return sorted(self._metrics)

    def __contains__(self, name):
        with self._lock:
            return name in self._metrics

    # -- export ------------------------------------------------------------

    def snapshot(self) -> dict:
        """JSON-ready {name: value | histogram-dict}; labeled children
        appear as ``name{k="v"}`` keys."""
        out = {}
        with self._lock:
            metrics = list(self._metrics.values())
        for m in metrics:
            for suffix, series in m._iter_series():
                key = m.name + suffix
                if isinstance(series, Histogram):
                    out[key] = series.to_dict()
                else:
                    out[key] = series.value
        return out

    def export_prometheus(self) -> str:
        """Prometheus text exposition v0.0.4. ``subsystem/name`` maps
        to ``paddle_subsystem_name``; histograms export as summaries
        (quantile labels + _sum/_count)."""
        lines = []
        with self._lock:
            metrics = list(self._metrics.values())
        for m in sorted(metrics, key=lambda x: x.name):
            prom = "paddle_" + m.name.replace("/", "_")
            if m.help:
                lines.append(f"# HELP {prom} {m.help}")
            ptype = {"counter": "counter", "gauge": "gauge",
                     "histogram": "summary"}[m.kind]
            lines.append(f"# TYPE {prom} {ptype}")
            for suffix, series in m._iter_series():
                if isinstance(series, Histogram):
                    if series.count == 0 and suffix == "" \
                            and m._children:
                        continue   # parent unused, only children carry data
                    for q in (0.5, 0.9, 0.99):
                        lbl = suffix[1:-1] + "," if suffix else ""
                        lines.append(
                            f'{prom}{{{lbl}quantile="{q}"}} '
                            f"{series.percentile(q * 100)}")
                    lines.append(f"{prom}_sum{suffix} {series.sum}")
                    lines.append(f"{prom}_count{suffix} {series.count}")
                else:
                    lines.append(f"{prom}{suffix} {series.value}")
        return "\n".join(lines) + "\n"

    def export(self, path=None) -> str:
        """Prometheus text; written ATOMICALLY when ``path`` given
        (a scrape mid-crash reads the previous complete exposition,
        never a torn one). Returns the text."""
        text = self.export_prometheus()
        if path is not None:
            from .trace import _atomic_write
            _atomic_write(path, lambda f: f.write(text))
        return text

    def export_json(self, path) -> str:
        """Atomic JSON snapshot; returns the path."""
        from .trace import _atomic_json_dump
        return _atomic_json_dump(self.snapshot(), path)


def _merge_suffix(suffix, label_key, label):
    """Fold a federation label into an existing Prometheus label
    suffix: ``"" -> {replica="0"}``, ``{k="v"} -> {replica="0",k="v"}``
    (the replica label leads, so federated series group by replica)."""
    mine = f'{label_key}="{label}"'
    if not suffix:
        return "{" + mine + "}"
    return "{" + mine + "," + suffix[1:-1] + "}"


def _percentiles(xs):
    """(p50, p90, p99) of a sample list with the registry's linear
    interpolation — shared by the merged-histogram render."""
    if not xs:
        return 0.0, 0.0, 0.0
    xs = sorted(xs)
    if len(xs) == 1:
        return xs[0], xs[0], xs[0]

    def pct(q):
        pos = (len(xs) - 1) * (q / 100.0)
        lo = int(pos)
        hi = min(lo + 1, len(xs) - 1)
        frac = pos - lo
        return xs[lo] * (1.0 - frac) + xs[hi] * frac

    return pct(50), pct(90), pct(99)


class FederatedRegistry(MetricsRegistry):
    """A registry that is also a FEDERATION POINT (ISSUE 13): it holds
    its own local metrics (``counter``/``gauge``/``histogram`` work
    exactly as on :class:`MetricsRegistry` — the fleet's ``fleet/*``
    vocabulary lives here) and aggregates any number of SOURCE
    registries — the fleet's per-replica private engine registries plus
    the process-wide default registry — into one labeled snapshot:

    - **counters** appear twice: per-replica as
      ``name{replica="3"}`` children AND summed into the unlabeled
      fleet total. Totals are MONOTONIC across supervised engine
      rebuilds and scale_down/eject: each source is read through a
      watermark (``base + raw``) that detects a replaced registry
      instance (a rebuilt engine starts a fresh registry at zero) and
      folds the old instance's last-seen value into the base — a
      restart can never make a fleet total go backwards.
    - **gauges** are inherently per-replica (summing two occupancy
      gauges means nothing): only the labeled children appear.
    - **histograms** appear per-replica AND as a deterministic MERGE:
      count/sum/min/max are summed exactly; the merged percentiles are
      computed over the concatenation of the sources' bounded
      reservoirs (sources visited in sorted label order — same fleet
      state, same answer).

    ``add_source(label, provider)`` takes a zero-arg callable returning
    the source registry, read live at every snapshot — so a supervised
    rebuild that swaps ``engine.metrics`` is picked up automatically.
    ``remove_source`` folds the source's final counter contributions
    into retained totals (scale_down must not erase history).

    Snapshots are atomic in the scrape sense: one ``snapshot()`` /
    ``export_prometheus()`` call serializes against concurrent
    snapshots (watermark state is shared) and reads each source metric
    under its own per-metric lock — the serving hot loop is never
    blocked by a scrape, and a scrape never reads a torn multi-field
    histogram.
    """

    def __init__(self, mirror=False, label_key="replica",
                 include_default=True):
        super().__init__(mirror=mirror)
        self._label_key = str(label_key)
        self._include_default = bool(include_default)
        self._fed_lock = threading.Lock()
        self._sources: dict[str, object] = {}       # label -> provider
        self._seen_reg: dict[str, int] = {}         # label -> id(reg)
        #: (label, series_key) -> [base, last_raw] counter watermarks
        self._marks: dict[tuple, list] = {}
        #: unlabeled total key -> counter mass of removed sources
        self._retired: dict[str, float] = {}

    # -- source registry ---------------------------------------------------

    def add_source(self, label, provider):
        """Register a source. ``provider`` is a zero-arg callable
        returning the source :class:`MetricsRegistry`, resolved at
        every snapshot (live — engine rebuilds swap the instance)."""
        label = str(label)
        with self._fed_lock:
            self._sources[label] = provider
        return label

    def remove_source(self, label):
        """Drop a source, folding its final counter contributions into
        the retained (unlabeled) totals so fleet counters stay
        monotonic across scale_down."""
        label = str(label)
        with self._fed_lock:
            self._sources.pop(label, None)
            self._seen_reg.pop(label, None)
            for (lbl, key), (base, last) in list(self._marks.items()):
                if lbl == label:
                    self._retired[key] = self._retired.get(key, 0) \
                        + base + last
                    del self._marks[(lbl, key)]

    def source_labels(self):
        with self._fed_lock:
            return sorted(self._sources)

    # -- the federated read ------------------------------------------------

    def _counter_contribution(self, label, key, raw):
        """Watermarked counter read (caller holds ``_fed_lock``)."""
        mark = self._marks.setdefault((label, key), [0, 0])
        if raw < mark[1]:
            # registry survived but the counter went backwards (an
            # explicit reset): fold what we saw into the base
            mark[0] += mark[1]
        mark[1] = raw
        return mark[0] + raw

    def _iter_source(self, label, provider, out_c, out_g, out_h):
        try:
            reg = provider()
        except Exception:  # noqa: BLE001 — a dead replica's provider
            reg = None     # must not fail the whole scrape
        if reg is None:
            # keep the retired-style contribution of whatever we last
            # saw, so totals never dip while a replica is mid-rebuild
            for (lbl, key), (base, last) in self._marks.items():
                if lbl == label:
                    out_c.setdefault(key, {"total": 0.0, "series": []})
                    out_c[key]["total"] += base + last
            return
        if id(reg) != self._seen_reg.get(label):
            # a REPLACED registry instance (engine rebuild): every
            # counter restarts from zero — bank the old values
            for (lbl, key), mark in self._marks.items():
                if lbl == label:
                    mark[0] += mark[1]
                    mark[1] = 0
            self._seen_reg[label] = id(reg)
        with reg._lock:
            metrics = list(reg._metrics.values())
        visited = set()
        for m in metrics:
            for suffix, series in m._iter_series():
                key = m.name + suffix
                visited.add(key)
                lsuffix = _merge_suffix(suffix, self._label_key, label)
                if isinstance(series, Histogram):
                    slot = out_h.setdefault(key, {
                        "count": 0, "sum": 0.0, "min": None,
                        "max": None, "samples": [], "series": []})
                    d = series.to_dict()
                    slot["count"] += d["count"]
                    slot["sum"] += d["sum"]
                    for agg, fn in (("min", min), ("max", max)):
                        if d[agg] is not None:
                            slot[agg] = d[agg] if slot[agg] is None \
                                else fn(slot[agg], d[agg])
                    slot["samples"].extend(series.samples())
                    slot["series"].append((lsuffix, d))
                elif isinstance(series, Counter):
                    v = self._counter_contribution(label, key,
                                                   series.value)
                    slot = out_c.setdefault(key, {"total": 0.0,
                                                  "series": []})
                    slot["total"] += v
                    slot["series"].append((lsuffix, v))
                else:
                    out_g.setdefault(key, []).append(
                        (lsuffix, series.value))
        # counter families the CURRENT registry has not (re-)minted —
        # a rebuilt engine that cancelled requests in a past life but
        # not this one — still carry banked watermark mass; emitting
        # only present families would make the fleet total DIP, the
        # exact violation the watermark exists to prevent
        for (lbl, key), (base, last) in self._marks.items():
            if lbl != label or key in visited:
                continue
            mass = base + last
            if not mass:
                continue
            slot = out_c.setdefault(key, {"total": 0.0, "series": []})
            slot["total"] += mass
            name = key.split("{")[0]
            suffix = key[len(name):]
            slot["series"].append(
                (_merge_suffix(suffix, self._label_key, label), mass))

    def _collect(self):
        """One atomic federated read: (counters, gauges, histograms)
        keyed by the UNLABELED series key. Sources are visited in
        sorted label order — the deterministic-merge contract."""
        out_c: dict[str, dict] = {}
        out_g: dict[str, list] = {}
        out_h: dict[str, dict] = {}
        with self._fed_lock:
            for key, mass in self._retired.items():
                out_c.setdefault(key, {"total": 0.0, "series": []})
                out_c[key]["total"] += mass
            for label in sorted(self._sources):
                self._iter_source(label, self._sources[label],
                                  out_c, out_g, out_h)
        return out_c, out_g, out_h

    def snapshot(self) -> dict:
        """The federated JSON-ready view: local + default-registry
        series unlabeled, per-source series as ``{replica="N"}``
        children, counter totals summed, histograms merged (module
        docstring). The flight recorder embeds THIS in bundles when a
        fleet is live, so a replica-death post-mortem shows sibling
        state."""
        out = {}
        if self._include_default and get_registry() is not self:
            out.update(get_registry().snapshot())
        out.update(super().snapshot())     # local (fleet/*) metrics
        out_c, out_g, out_h = self._collect()
        for key, slot in sorted(out_c.items()):
            out[key] = out.get(key, 0) + slot["total"]
            for lsuffix, v in slot["series"]:
                out[key.split("{")[0] + lsuffix] = v
        for key, series in sorted(out_g.items()):
            for lsuffix, v in series:
                out[key.split("{")[0] + lsuffix] = v
        for key, slot in sorted(out_h.items()):
            p50, p90, p99 = _percentiles(slot["samples"])
            out[key] = {"count": slot["count"],
                        "sum": round(slot["sum"], 6),
                        "min": slot["min"], "max": slot["max"],
                        "p50": round(p50, 6), "p90": round(p90, 6),
                        "p99": round(p99, 6)}
            for lsuffix, d in slot["series"]:
                out[key.split("{")[0] + lsuffix] = d
        return out

    def export_prometheus(self) -> str:
        """Prometheus text over the federated view: local + default
        series as-is, then each federated family with its summed total
        and ``replica``-labeled children."""
        parts = []
        if self._include_default and get_registry() is not self:
            parts.append(get_registry().export_prometheus())
        parts.append(super().export_prometheus())
        # a family already rendered by the local/default blocks must
        # not get a SECOND # TYPE header from the federated block —
        # Prometheus parsers reject duplicate family headers
        seen = set()
        for p in parts:
            for line in p.splitlines():
                if line.startswith("# TYPE "):
                    seen.add(line.split()[2])
        lines = []
        out_c, out_g, out_h = self._collect()
        kinds = catalog()

        def header(key, ptype):
            name = key.split("{")[0]
            prom = "paddle_" + name.replace("/", "_")
            if prom not in seen:
                seen.add(prom)
                help_ = kinds.get(name, ("", ""))[1]
                if help_:
                    lines.append(f"# HELP {prom} {help_}")
                lines.append(f"# TYPE {prom} {ptype}")
            return prom, key[len(name):]

        for key, slot in sorted(out_c.items()):
            prom, suffix = header(key, "counter")
            lines.append(f"{prom}{suffix} {slot['total']}")
            for lsuffix, v in slot["series"]:
                lines.append(f"{prom}{lsuffix} {v}")
        for key, series in sorted(out_g.items()):
            prom, _ = header(key, "gauge")
            for lsuffix, v in series:
                lines.append(f"{prom}{lsuffix} {v}")
        for key, slot in sorted(out_h.items()):
            prom, suffix = header(key, "summary")
            p50, p90, p99 = _percentiles(slot["samples"])
            for q, v in ((0.5, p50), (0.9, p90), (0.99, p99)):
                lbl = suffix[1:-1] + "," if suffix else ""
                lines.append(f'{prom}{{{lbl}quantile="{q}"}} {v}')
            lines.append(f"{prom}_sum{suffix} {slot['sum']}")
            lines.append(f"{prom}_count{suffix} {slot['count']}")
        parts.append("\n".join(lines) + ("\n" if lines else ""))
        return "".join(parts)


_registry = MetricsRegistry(mirror=True)


def get_registry() -> MetricsRegistry:
    """The process-wide default registry (tracer-mirroring). Component
    instances (e.g. a serving engine) own private
    ``MetricsRegistry()``\\ s instead so their gauges stay scoped."""
    return _registry
