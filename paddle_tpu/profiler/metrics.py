"""Typed metrics registry — the production observability substrate.

Every quantitative claim the runtime makes about itself (serving
latency percentiles, fit pipeline gauges, elastic restart accounting,
flight-recorder health) flows through ONE registry of typed metrics:

- :class:`Counter` — monotonically increasing totals (``inc``), float
  or int, exact under concurrent increment (per-metric lock; the
  prefetcher and scheduler threads bump counters concurrently).
- :class:`Gauge` — last-written point-in-time value (``set``).
- :class:`Histogram` — streaming distribution with a BOUNDED
  reservoir (Vitter's algorithm R): ``observe()`` is O(1), memory is
  fixed at ``capacity`` samples forever, and ``percentile(q)`` stays
  statistically faithful over millions of observations. This replaces
  the unbounded per-request latency sample lists the serving engine
  used to grow (``_ttft_ms``/``_itl_ms``, serving.py) — a long-lived
  engine's memory now stays flat.

Naming is enforced: every metric is ``subsystem/name``
(``serving/tokens_emitted``, ``hapi/input_wait_ms``, ``obs/overhead_frac``)
— ``tools/check_metric_names.py`` lints the convention and that every
registered name is documented in docs/observability.md.

Export surfaces (both atomic — tmp + fsync + rename, the checkpoint
invariant, so a scraper or post-mortem never reads a torn file):

- ``registry.snapshot()`` → plain dict (JSON-ready; the flight
  recorder embeds it in crash bundles);
- ``registry.export(path)`` → Prometheus text exposition v0.0.4
  (counters/gauges as-is, histograms as summaries with quantile
  labels);
- ``registry.export_json(path)`` → the snapshot, atomically.

Two registry scopes exist: the process-wide default
(:func:`get_registry` — hapi fit, elastic/restart counters, jit
compile accounting) whose updates MIRROR into the structured tracer
when tracing is enabled (so chrome-trace exports keep carrying the
gauges, exactly as before this registry existed), and per-component
instances (each ``ContinuousBatchingEngine`` owns one, so two engines
in one process never cross-pollute and ``gauges()`` stays
per-engine).

Deliberately stdlib-only (no jax): imported from hot paths.
"""

from __future__ import annotations

import random
import re
import threading
import zlib

__all__ = ["Counter", "Gauge", "Histogram", "MetricsRegistry",
           "get_registry", "declare", "catalog", "catalog_markdown",
           "METRIC_NAME_RE"]

#: the ``subsystem/name`` convention, linted by
#: tools/check_metric_names.py
METRIC_NAME_RE = re.compile(r"^[a-z][a-z0-9_]*/[a-z][a-z0-9_]*$")

#: process-wide name -> (kind, help) vocabulary. Every registration in
#: ANY registry lands here (metric NAMES are a global vocabulary even
#: when their values are per-component); :func:`declare` populates it
#: at import time so the docs table and the lint gate can see names
#: before any component is constructed.
_CATALOG: dict[str, tuple[str, str]] = {}
_CATALOG_LOCK = threading.Lock()


def _check_name(name: str) -> str:
    if not METRIC_NAME_RE.match(name):
        raise ValueError(
            f"metric name {name!r} violates the subsystem/name "
            "convention (lowercase [a-z0-9_], exactly one '/'); see "
            "docs/observability.md")
    return name


def declare(name: str, kind: str, help: str) -> str:  # noqa: A002
    """Register ``name`` in the process-wide metric catalog without
    creating a metric. Modules declare their vocabulary at import time
    (literal arguments — ``tools/check_metric_names.py`` parses these
    statically); the registry pulls help text from here when a metric
    is later instantiated."""
    _check_name(name)
    if kind not in ("counter", "gauge", "histogram"):
        raise ValueError(f"unknown metric kind {kind!r}")
    with _CATALOG_LOCK:
        prev = _CATALOG.get(name)
        if prev is not None and prev[0] != kind:
            raise ValueError(
                f"metric {name!r} re-declared as {kind} (was {prev[0]})")
        _CATALOG[name] = (kind, help)
    return name


def catalog() -> dict[str, tuple[str, str]]:
    """A copy of the process-wide name -> (kind, help) catalog."""
    with _CATALOG_LOCK:
        return dict(_CATALOG)


def catalog_markdown() -> str:
    """The docs/observability.md metric table, generated from the
    catalog (one row per declared metric, sorted)."""
    lines = ["| metric | kind | meaning |", "|---|---|---|"]
    for name in sorted(catalog()):
        kind, help_ = _CATALOG[name]
        lines.append(f"| `{name}` | {kind} | {help_} |")
    return "\n".join(lines)


def _mirror_to_trace(name, value, **args):
    """Mirror a counter/gauge update into the structured tracer (one
    enabled-check; zero cost while tracing is off). Keeps chrome-trace
    exports carrying the same gauge streams they did before the
    registry existed."""
    from .trace import get_tracer
    tr = get_tracer()
    if tr.enabled:
        tr.counter(name, value, **args)


class _Metric:
    """Shared base: name, help, per-metric lock, label children."""

    kind = "?"

    def __init__(self, name, help="", mirror=False):  # noqa: A002
        self.name = _check_name(name)
        self.help = help
        self._mirror = bool(mirror)
        self._lock = threading.Lock()
        self._children: dict[tuple, _Metric] = {}
        with _CATALOG_LOCK:
            prev = _CATALOG.get(name)
            if prev is not None and prev[0] != self.kind:
                raise ValueError(
                    f"metric {name!r} registered as {self.kind} but "
                    f"declared as {prev[0]}")
            if prev is None or (help and not prev[1]):
                _CATALOG[name] = (self.kind, help or
                                  (prev[1] if prev else ""))
            elif not help:
                self.help = prev[1]

    def labels(self, **kv):
        """The child metric for a label set (Prometheus idiom):
        ``reg.counter("serving/requests").labels(outcome="eos").inc()``.
        Children share the parent's config and appear in snapshots as
        ``name{k="v"}``."""
        key = tuple(sorted(kv.items()))
        with self._lock:
            child = self._children.get(key)
            if child is None:
                child = type(self)(self.name, self.help,
                                   mirror=self._mirror,
                                   **self._child_kwargs())
                child._label_kv = key
                self._children[key] = child
            return child

    def _child_kwargs(self):
        return {}

    def _label_suffix(self):
        kv = getattr(self, "_label_kv", ())
        if not kv:
            return ""
        inner = ",".join(f'{k}="{v}"' for k, v in kv)
        return "{" + inner + "}"

    def _iter_series(self):
        """(label_suffix, metric) for self + every labeled child."""
        yield self._label_suffix(), self
        with self._lock:
            children = list(self._children.values())
        for c in children:
            yield c._label_suffix(), c


class Counter(_Metric):
    """Monotonic total. ``inc`` is exact under concurrent callers."""

    kind = "counter"

    def __init__(self, name, help="", mirror=False):  # noqa: A002
        super().__init__(name, help, mirror=mirror)
        self._value = 0

    def inc(self, n=1, **args):
        with self._lock:
            self._value += n
            v = self._value
        if self._mirror:
            _mirror_to_trace(self.name, v, **args)
        return v

    def set(self, v, **args):
        """Direct assignment — reset (``reset_gauges``) and restored
        state (ledger reload) only; normal accounting uses ``inc``."""
        with self._lock:
            self._value = v
        if self._mirror:
            _mirror_to_trace(self.name, v, **args)

    @property
    def value(self):
        with self._lock:
            return self._value


class Gauge(_Metric):
    """Point-in-time value; last write wins."""

    kind = "gauge"

    def __init__(self, name, help="", mirror=False):  # noqa: A002
        super().__init__(name, help, mirror=mirror)
        self._value = 0.0

    def set(self, v, **args):
        with self._lock:
            self._value = v
        if self._mirror:
            _mirror_to_trace(self.name, v, **args)

    def inc(self, n=1, **args):
        with self._lock:
            self._value += n
            v = self._value
        if self._mirror:
            _mirror_to_trace(self.name, v, **args)
        return v

    @property
    def value(self):
        with self._lock:
            return self._value


class Histogram(_Metric):
    """Streaming distribution over a BOUNDED reservoir (Vitter's
    algorithm R): after ``capacity`` samples, each new observation
    replaces a uniformly-random slot with probability capacity/count —
    the reservoir stays a uniform sample of the whole stream, memory
    stays fixed, and percentiles stay faithful. count/sum/min/max are
    exact (not sampled). Deterministically seeded per instance so
    tests are reproducible."""

    kind = "histogram"

    def __init__(self, name, help="", mirror=False,  # noqa: A002
                 capacity=1024):
        super().__init__(name, help, mirror=mirror)
        self.capacity = int(capacity)
        if self.capacity < 1:
            raise ValueError("histogram capacity must be >= 1")
        # crc32, not hash(): PYTHONHASHSEED must not change which
        # reservoir slots a replayed stream evicts
        self._rng = random.Random(0xA5F00D ^ zlib.crc32(name.encode()))
        self._samples: list[float] = []
        self.count = 0
        self.sum = 0.0
        self.min = None
        self.max = None

    def _child_kwargs(self):
        return {"capacity": self.capacity}

    def observe(self, v, **_args):
        v = float(v)
        with self._lock:
            self.count += 1
            self.sum += v
            if self.min is None or v < self.min:
                self.min = v
            if self.max is None or v > self.max:
                self.max = v
            if len(self._samples) < self.capacity:
                self._samples.append(v)
            else:
                j = self._rng.randrange(self.count)
                if j < self.capacity:
                    self._samples[j] = v

    def percentile(self, q):
        """q in [0, 100]; 0.0 when empty (the legacy gauge contract)."""
        with self._lock:
            if not self._samples:
                return 0.0
            xs = sorted(self._samples)
        if len(xs) == 1:
            return xs[0]
        # linear interpolation (numpy default) without importing numpy
        pos = (len(xs) - 1) * (q / 100.0)
        lo = int(pos)
        hi = min(lo + 1, len(xs) - 1)
        frac = pos - lo
        return xs[lo] * (1.0 - frac) + xs[hi] * frac

    def reset(self):
        with self._lock:
            self._samples = []
            self.count = 0
            self.sum = 0.0
            self.min = None
            self.max = None

    @property
    def sample_count(self):
        """Resident reservoir size — bounded by ``capacity`` forever
        (the memory-flat regression tests pin this)."""
        with self._lock:
            return len(self._samples)

    def to_dict(self):
        with self._lock:
            n = self.count
            s = self.sum
            mn, mx = self.min, self.max
        return {"count": n, "sum": round(s, 6),
                "min": mn, "max": mx,
                "p50": round(self.percentile(50), 6),
                "p90": round(self.percentile(90), 6),
                "p99": round(self.percentile(99), 6)}


class MetricsRegistry:
    """Get-or-create home for typed metrics (see module docstring).
    ``mirror=True`` (the process-wide default registry) echoes every
    counter/gauge update into the structured tracer while tracing is
    enabled."""

    def __init__(self, mirror=False):
        self._metrics: dict[str, _Metric] = {}
        self._lock = threading.Lock()
        self._mirror = bool(mirror)

    def _get_or_create(self, cls, name, help, **kw):  # noqa: A002
        with self._lock:
            m = self._metrics.get(name)
            if m is not None:
                if not isinstance(m, cls):
                    raise ValueError(
                        f"metric {name!r} already registered as "
                        f"{m.kind}, not {cls.kind}")
                return m
            m = cls(name, help, mirror=self._mirror, **kw)
            self._metrics[name] = m
            return m

    def counter(self, name, help="") -> Counter:  # noqa: A002
        return self._get_or_create(Counter, name, help)

    def gauge(self, name, help="") -> Gauge:  # noqa: A002
        return self._get_or_create(Gauge, name, help)

    def histogram(self, name, help="",  # noqa: A002
                  capacity=1024) -> Histogram:
        return self._get_or_create(Histogram, name, help,
                                   capacity=capacity)

    def get(self, name):
        with self._lock:
            return self._metrics.get(name)

    def names(self):
        with self._lock:
            return sorted(self._metrics)

    def __contains__(self, name):
        with self._lock:
            return name in self._metrics

    # -- export ------------------------------------------------------------

    def snapshot(self) -> dict:
        """JSON-ready {name: value | histogram-dict}; labeled children
        appear as ``name{k="v"}`` keys."""
        out = {}
        with self._lock:
            metrics = list(self._metrics.values())
        for m in metrics:
            for suffix, series in m._iter_series():
                key = m.name + suffix
                if isinstance(series, Histogram):
                    out[key] = series.to_dict()
                else:
                    out[key] = series.value
        return out

    def export_prometheus(self) -> str:
        """Prometheus text exposition v0.0.4. ``subsystem/name`` maps
        to ``paddle_subsystem_name``; histograms export as summaries
        (quantile labels + _sum/_count)."""
        lines = []
        with self._lock:
            metrics = list(self._metrics.values())
        for m in sorted(metrics, key=lambda x: x.name):
            prom = "paddle_" + m.name.replace("/", "_")
            if m.help:
                lines.append(f"# HELP {prom} {m.help}")
            ptype = {"counter": "counter", "gauge": "gauge",
                     "histogram": "summary"}[m.kind]
            lines.append(f"# TYPE {prom} {ptype}")
            for suffix, series in m._iter_series():
                if isinstance(series, Histogram):
                    if series.count == 0 and suffix == "" \
                            and m._children:
                        continue   # parent unused, only children carry data
                    for q in (0.5, 0.9, 0.99):
                        lbl = suffix[1:-1] + "," if suffix else ""
                        lines.append(
                            f'{prom}{{{lbl}quantile="{q}"}} '
                            f"{series.percentile(q * 100)}")
                    lines.append(f"{prom}_sum{suffix} {series.sum}")
                    lines.append(f"{prom}_count{suffix} {series.count}")
                else:
                    lines.append(f"{prom}{suffix} {series.value}")
        return "\n".join(lines) + "\n"

    def export(self, path=None) -> str:
        """Prometheus text; written ATOMICALLY when ``path`` given
        (a scrape mid-crash reads the previous complete exposition,
        never a torn one). Returns the text."""
        text = self.export_prometheus()
        if path is not None:
            from .trace import _atomic_write
            _atomic_write(path, lambda f: f.write(text))
        return text

    def export_json(self, path) -> str:
        """Atomic JSON snapshot; returns the path."""
        from .trace import _atomic_json_dump
        return _atomic_json_dump(self.snapshot(), path)


_registry = MetricsRegistry(mirror=True)


def get_registry() -> MetricsRegistry:
    """The process-wide default registry (tracer-mirroring). Component
    instances (e.g. a serving engine) own private
    ``MetricsRegistry()``\\ s instead so their gauges stay scoped."""
    return _registry
