"""Shared HTTP response skeleton for the repo's stdlib-only servers
(ISSUE 15 satellite).

Two front doors serve HTTP out of a serving process — the
observability exposition (``profiler/exposition.py``, ``http.server``
in a daemon thread) and the OpenAI-compatible API server
(``inference/api_server.py``, ``asyncio`` streams). Both must hold the
same response invariants, and keeping the skeleton in ONE place is
what stops them drifting:

- **materialize-before-send** — every non-streaming response body is
  fully encoded and measured (``Content-Length``) before the first
  byte leaves the process, so a client never reads a torn document
  (the same invariant the atomic file exports hold);
- **guarded sections** — ``/statusz`` documents are assembled by
  :func:`evaluate_sections`: each named provider is evaluated inside
  its own try, a provider raising mid-churn degrades to an
  ``{"error": ...}`` stanza, and the scrape always parses.

``exposition.py`` re-exports :func:`evaluate_sections` (its historical
home) so existing imports keep working.
"""

from __future__ import annotations

__all__ = ["REASONS", "evaluate_sections", "materialize_response",
           "http1_head", "http1_response"]

#: the status lines the two servers actually emit — a code outside
#: this table renders with a generic reason, never a KeyError
REASONS = {
    200: "OK",
    400: "Bad Request",
    404: "Not Found",
    405: "Method Not Allowed",
    429: "Too Many Requests",
    499: "Client Closed Request",
    500: "Internal Server Error",
    502: "Bad Gateway",
    503: "Service Unavailable",
    504: "Gateway Timeout",
}


def evaluate_sections(sections) -> dict:
    """Evaluate named section providers into one dict, each GUARDED —
    a provider raising mid-churn degrades to an ``{"error": ...}``
    stanza instead of tearing the document. The ONE loop behind the
    exposition ``/statusz`` render, ``ServingFleet.statusz()`` and the
    API server's ``/statusz``."""
    doc = {}
    for name, provider in dict(sections).items():
        try:
            doc[name] = provider()
        except Exception as exc:  # noqa: BLE001 — degrade per section
            doc[name] = {"error": f"{type(exc).__name__}: {exc}"}
    return doc


def materialize_response(code, body, ctype, extra_headers=()):
    """Encode + measure a response BEFORE anything is sent.

    Returns ``(code, headers, data)`` where ``headers`` is a list of
    ``(name, value)`` pairs starting with ``Content-Type`` and a
    ``Content-Length`` computed from the fully materialized ``data``
    bytes — the caller writes headers then ``data`` verbatim, so a
    handler exception can no longer tear a document mid-send."""
    data = body if isinstance(body, bytes) else str(body).encode("utf-8")
    headers = [("Content-Type", ctype),
               ("Content-Length", str(len(data)))]
    headers.extend(extra_headers)
    return code, headers, data


def http1_head(code, headers) -> bytes:
    """Serialize an HTTP/1.1 status line + header block (the raw-
    socket path: the asyncio API server owns its own framing)."""
    reason = REASONS.get(code, "Unknown")
    lines = [f"HTTP/1.1 {int(code)} {reason}"]
    lines.extend(f"{k}: {v}" for k, v in headers)
    return ("\r\n".join(lines) + "\r\n\r\n").encode("latin-1")


def http1_response(code, body, ctype, extra_headers=()) -> bytes:
    """One fully materialized HTTP/1.1 response (head + body bytes),
    ``Connection: close`` framing — the API server's non-streaming
    send path."""
    code, headers, data = materialize_response(code, body, ctype,
                                               extra_headers)
    headers.append(("Connection", "close"))
    return http1_head(code, headers) + data
