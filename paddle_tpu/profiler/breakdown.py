"""In-program step-breakdown harness: section ablation -> attribution.

Host spans cannot see inside one compiled XLA program, so per-section
time inside a jitted train step is measured the way the round-4 CB
breakdown was (BASELINE.md): compile N+1 VARIANTS of the step — the full
program plus one with each section knocked out (replaced by a
shape-preserving placeholder that XLA cannot constant-fold away) — time
each, and attribute ``t(section) = t(full) - t(without section)``.

Attribution caveats (documented, not hidden):

- Sections that XLA overlaps (e.g. an all-to-all hidden behind matmuls)
  attribute only their EXPOSED time — which is the number that matters
  for optimization priority.
- If the per-section attributions sum past the full step time (overlap
  reclaimed twice), they are scaled proportionally so the table always
  sums to 100%; the residual is reported as ``other``.
- Ablated programs produce garbage NUMERICS by design; the harness must
  never share compiled programs or parameters with a real training run.

``moe_step_breakdown`` wires this into the MoE stack: gating / sort /
a2a / expert-matmul sections via ``ops.moe.moe_ablation``.
"""

from __future__ import annotations

import time

from . import cost as _cost
from .trace import TraceEvent, get_tracer

__all__ = ["StepBreakdown", "ablation_breakdown", "moe_step_breakdown"]


class StepBreakdown:
    """Machine-readable per-section step attribution.

    ``rows`` is a list of dicts — one per section plus ``other`` — with
    ``section``, ``ms``, ``frac`` (fractions sum to 1.0), and, when
    costs were provided, ``flops``/``bytes``/``mfu``/``bound``.
    """

    def __init__(self, step_ms: float, rows: list, meta: dict | None = None):
        self.step_ms = step_ms
        self.rows = rows
        self.meta = dict(meta or {})

    def to_dict(self) -> dict:
        return {"step_ms": round(self.step_ms, 4),
                "sections": self.rows, "meta": self.meta}

    def to_markdown(self) -> str:
        lines = ["| section | ms | % of step | MFU | bound |",
                 "|---|---|---|---|---|"]
        for r in self.rows:
            mfu = f"{r['mfu'] * 100:.1f}%" if r.get("mfu") is not None \
                else "—"
            lines.append(
                f"| {r['section']} | {r['ms']:.2f} | "
                f"{r['frac'] * 100:.1f}% | {mfu} | "
                f"{r.get('bound', '—')} |")
        lines.append(f"| **step** | {self.step_ms:.2f} | 100% | | |")
        return "\n".join(lines)

    def emit(self, tracer=None):
        """Record the breakdown into a tracer as back-to-back spans (one
        synthetic timeline slice per section) + per-section gauges, so
        ``export_chrome_trace`` shows the attribution visually."""
        tracer = tracer or get_tracer()
        if not tracer.enabled:
            # never inject synthetic spans into a disabled tracer (they
            # would leak into a later, unrelated tracing session)
            return self
        t0 = (time.perf_counter() - tracer._epoch) * 1e6
        off = 0.0
        for r in self.rows:
            args = {k: r[k] for k in ("frac", "flops", "bytes", "mfu",
                                      "bound") if r.get(k) is not None}
            tracer._record(TraceEvent(
                name=f"breakdown/{r['section']}", ph="X", cat="breakdown",
                ts=t0 + off, dur=r["ms"] * 1e3, args=args))
            tracer.counter(f"breakdown/{r['section']}_frac", r["frac"])
            off += r["ms"] * 1e3
        return self

    def export_chrome_trace(self, path) -> str:
        """One-shot chrome-trace export of just this breakdown."""
        from .trace import Tracer
        t = Tracer(enabled=True)
        self.emit(t)
        return t.export_chrome_trace(path)


def _timeit(run, steps, warmup) -> float:
    """Min over individually-timed steps: attribution subtracts two
    close numbers, and min filters one-off dispatch spikes (the tunnel's
    ~100 ms RTT variance) far better than a mean over few steps — the
    same reason bench.py's decode metric takes min over reps."""
    for _ in range(warmup):
        run()
    best = float("inf")
    for _ in range(steps):
        t0 = time.perf_counter()
        run()
        best = min(best, time.perf_counter() - t0)
    return best


def ablation_breakdown(build_step, sections, steps=4, warmup=2,
                       costs=None, peaks=None, meta=None) -> StepBreakdown:
    """Generic attribution harness.

    build_step(ablate: frozenset[str]) -> zero-arg callable running ONE
    step and BLOCKING until device work completes (an unsynced step
    times dispatch, not execution). Called once per variant:
    ``frozenset()`` for the full step, ``{s}`` for each section.

    costs: optional {section: SectionCost} giving each row its MFU +
    roofline columns (profiler.cost.moe_section_costs builds these).
    """
    sections = list(sections)
    peaks = peaks or _cost.device_peaks()
    full = _timeit(build_step(frozenset()), steps, warmup)
    attr = {}
    for s in sections:
        without = _timeit(build_step(frozenset((s,))), steps, warmup)
        attr[s] = max(full - without, 0.0)
    total_attr = sum(attr.values())
    if total_attr > full > 0:
        # overlapped sections double-counted their reclaimed time:
        # scale so the table still sums to the measured step
        scale = full / total_attr
        attr = {s: v * scale for s, v in attr.items()}
        total_attr = full
    other = max(full - total_attr, 0.0)

    rows = []
    for s in sections + ["other"]:
        sec_s = other if s == "other" else attr[s]
        row = {"section": s, "ms": round(sec_s * 1e3, 4),
               "frac": round(sec_s / full, 6) if full else 0.0}
        c = (costs or {}).get(s)
        if c is not None:
            row["flops"] = c.flops
            row["bytes"] = c.bytes
            row["mfu"] = round(_cost.mfu(c.flops, sec_s, peaks.flops), 6) \
                if sec_s else None
            row["bound"] = _cost.roofline(c.flops, c.bytes, peaks)["bound"]
        rows.append(row)
    # force exact 100%: dump rounding residue into 'other'
    resid = 1.0 - sum(r["frac"] for r in rows)
    rows[-1]["frac"] = round(rows[-1]["frac"] + resid, 6)
    m = {"steps": steps, "warmup": warmup, "device_kind": peaks.kind,
         "peak_flops": peaks.flops}
    m.update(meta or {})
    return StepBreakdown(full * 1e3, rows, m)


def moe_step_breakdown(model, input_ids, sections=None, steps=4,
                       warmup=2) -> StepBreakdown:
    """Attribute a MoE train step: gating / sort / a2a / expert-matmul /
    other, with per-section MFU and roofline columns.

    model: a CausalLM whose sparse FFN routes through ``ops.moe``
    (Qwen2MoeForCausalLM, MoELayer users). input_ids: [B, S+1] Tensor
    (labels = inputs, the bench convention). Each ablation variant is
    compiled fresh via ``jit.to_static`` — parameters are shared but
    gradients are cleared every step, so the model is unchanged after.

    The a2a section only attributes under expert parallelism; on a
    single device it reports ~0 (present in the table for schema
    stability — the acceptance schema is gating/sort/a2a/expert-matmul/
    other summing to 100%).
    """
    from ..framework.core import Tensor  # noqa: F401 (typing aid)
    from ..jit import to_static
    from ..ops import moe as moe_ops

    cfg = model.config
    if sections is None:
        sections = ["gating", "sort", "a2a", "expert_matmul"]

    batch, seqp1 = input_ids.shape
    tokens = batch * (seqp1 - 1)
    n_moe_layers = getattr(cfg, "num_hidden_layers", 1)
    first_dense = getattr(cfg, "first_k_dense_replace", 0)
    costs = _cost.moe_section_costs(
        tokens, cfg.hidden_size,
        getattr(cfg, "moe_intermediate_size", cfg.hidden_size),
        getattr(cfg, "num_experts", getattr(cfg, "n_routed_experts", 1)),
        getattr(cfg, "num_experts_per_tok", 1),
        num_moe_layers=max(n_moe_layers - first_dense, 1),
        capacity_factor=getattr(cfg, "capacity_factor", None),
        dropless=getattr(cfg, "moe_dropless", False), train=True)

    def build_step(ablate):
        def step_fn(ids):
            _, loss = model(ids, labels=ids)
            loss.backward()
            gsum = None
            for p in model.parameters():
                if p.grad is not None:
                    s = p.grad.flatten()[0].astype("float32")
                    gsum = s if gsum is None else gsum + s
            for p in model.parameters():
                p.clear_grad()
            return loss, gsum

        fn = to_static(step_fn)           # fresh program per variant

        def run():
            # the ablation context must cover the first (tracing) call:
            # the knocked-out sections are a trace-time decision
            with moe_ops.moe_ablation(ablate):
                loss, _ = fn(input_ids)
            float(loss.item())            # true device sync
        return run

    bd = ablation_breakdown(
        build_step, sections, steps=steps, warmup=warmup, costs=costs,
        meta={"tokens_per_step": tokens,
              "model": type(model).__name__,
              "accounting": "model FLOPs only; remat re-forward time "
                            "counted, FLOPs not (BASELINE.md caveat)"})
    return bd
