"""Cost accounting: FLOPs/bytes per section from static shape info.

Gives every trace span and breakdown row its denominator: per-section
MFU (achieved / peak FLOP/s) and a roofline classification (compute- vs
memory-bound from arithmetic intensity vs the chip's ridge point).

Accounting conventions (the ones BASELINE.md already uses):

- MFU counts MODEL FLOPs. Rematerialization's re-forward work is real
  hardware time but NOT added to FLOPs — that would report HFU and
  inflate the metric (BASELINE.md round-4/5 accounting note). The
  asymmetry is deliberate and conservative: remat-heavy configs show
  LOWER MFU than the hardware's busy fraction.
- Train steps count 3x the forward matmul FLOPs (1 fwd + 2 fwd-equiv
  backward), the standard 6·N·tokens convention.
- Byte counts are algorithm-level (operands read once + result written
  once), not XLA-schedule-level; they bound the roofline, they do not
  model cache reuse.

Peaks are per device kind (same table ``bench.py`` reports MFU against)
plus HBM bandwidth for the ridge point.
"""

from __future__ import annotations

from dataclasses import dataclass

__all__ = ["SectionCost", "Peaks", "device_peaks", "peak_flops",
           "matmul_cost", "attention_cost", "grouped_matmul_cost",
           "transformer_step_flops", "moe_section_costs", "mfu",
           "roofline", "rms_norm_cost", "swiglu_cost",
           "fused_linear_ce_cost"]


@dataclass
class SectionCost:
    """FLOPs + bytes attributed to one program section."""

    flops: float = 0.0
    bytes: float = 0.0

    def __add__(self, other: "SectionCost") -> "SectionCost":
        return SectionCost(self.flops + other.flops,
                           self.bytes + other.bytes)

    def __mul__(self, k) -> "SectionCost":
        return SectionCost(self.flops * k, self.bytes * k)

    __rmul__ = __mul__

    def to_dict(self) -> dict:
        return {"flops": self.flops, "bytes": self.bytes}


@dataclass
class Peaks:
    """Per-chip peaks: bf16 matmul FLOP/s and HBM bandwidth (B/s)."""

    flops: float
    hbm_bw: float
    kind: str = "unknown"

    @property
    def ridge(self) -> float:
        """Arithmetic intensity (FLOPs/byte) where the chip turns
        compute-bound."""
        return self.flops / self.hbm_bw


# bf16 peak FLOP/s and HBM GB/s per TPU generation (public spec sheets;
# order matters below: 'v6 lite' must match before generic 'v5'/'lite')
_PEAK_TABLE = (
    ("v6", Peaks(918e12, 1640e9, "v6e")),
    ("v5p", Peaks(459e12, 2765e9, "v5p")),
    ("v5 p", Peaks(459e12, 2765e9, "v5p")),
    ("v5", Peaks(197e12, 819e9, "v5e")),
    ("lite", Peaks(197e12, 819e9, "v5e")),
    ("v4", Peaks(275e12, 1228e9, "v4")),
)
_FALLBACK = Peaks(50e12, 100e9, "unknown")   # CPU/unknown: line still prints


def device_peaks(device=None) -> Peaks:
    """Peaks for a jax device (default: first visible device). Unknown
    kinds (CPU smoke runs) get a fallback so records still emit."""
    if device is None:
        try:
            import jax
            device = jax.devices()[0]
        except Exception:
            return _FALLBACK
    kind = getattr(device, "device_kind", "").lower()
    for key, peaks in _PEAK_TABLE:
        if key in kind:
            return peaks
    return _FALLBACK


def peak_flops(device=None) -> float:
    return device_peaks(device).flops


def matmul_cost(m, k, n, *, batch=1, dtype_bytes=2) -> SectionCost:
    """[m, k] @ [k, n] (optionally batched): 2mkn FLOPs, operands read
    once + result written once."""
    return SectionCost(
        flops=2.0 * batch * m * k * n,
        bytes=float(batch) * dtype_bytes * (m * k + k * n + m * n))


def grouped_matmul_cost(rows, d, h, num_experts, *,
                        dtype_bytes=2) -> SectionCost:
    """Grouped matmul over an [E, d, h] bank: ``rows`` total row-tiles
    worth of tokens, each contracting [d] -> [h]. The whole weight bank
    streams once per call (the Pallas kernel's revisit guarantee —
    ops/pallas/grouped_matmul.py), not once per tile."""
    return SectionCost(
        flops=2.0 * rows * d * h,
        bytes=dtype_bytes * (rows * d + num_experts * d * h + rows * h))


def attention_cost(batch, q_len, heads, head_dim, kv_len=None, *,
                   causal=True, dtype_bytes=2) -> SectionCost:
    """QK^T + AV FLOPs (the 12·L·B·S²·d convention divides the same
    way: 4·B·H·S·S_kv·dh per layer, halved when causal masking skips
    the upper triangle)."""
    kv_len = q_len if kv_len is None else kv_len
    f = 4.0 * batch * heads * q_len * kv_len * head_dim
    if causal and kv_len == q_len:
        f *= 0.5
    b = dtype_bytes * batch * heads * (q_len + 2 * kv_len + q_len) \
        * head_dim
    return SectionCost(flops=f, bytes=float(b))


def transformer_step_flops(n_params, tokens, num_layers, batch, seq,
                           hidden) -> float:
    """Train-step model FLOPs: 6·N·tokens + the S² attention term —
    the exact formula bench.py's MFU headline uses."""
    return 6.0 * n_params * tokens + 12.0 * num_layers * batch \
        * seq * seq * hidden


def moe_section_costs(tokens, d_model, d_hidden, num_experts, top_k, *,
                      num_moe_layers=1, capacity_factor=None,
                      dropless=True, bm=128, train=True,
                      dtype_bytes=2) -> dict:
    """Per-section costs for one MoE step's sparse-FFN stack —
    the denominators of the gating / sort / a2a / expert-matmul
    breakdown (profiler.breakdown.moe_step_breakdown).

    ``rows`` is the number of expert-FFN input rows the hardware
    actually executes: tokens·k (+ <= E·bm tile padding) for dropless,
    capacity_factor·tokens·k for the capacity formulation (its padding
    is executed work — the measured dropless-vs-capacity gap,
    BASELINE.md config 5). ``train=True`` multiplies matmul FLOPs by 3
    (fwd + 2x bwd); remat re-forwards are deliberately NOT counted
    (module docstring)."""
    T, d, h, E, k = tokens, d_model, d_hidden, num_experts, top_k
    if dropless:
        rows = T * k + E * bm // 2          # expected tile padding
    else:
        cf = 1.25 if capacity_factor is None else float(capacity_factor)
        rows = int(cf * T * k)
    mult = 3.0 if train else 1.0
    gating = matmul_cost(T, d, E, dtype_bytes=4) * mult      # fp32 router
    # sort/dispatch: index math is negligible FLOPs; the cost is moving
    # every routed row in and out of the expert layout (two gathers)
    sort = SectionCost(flops=0.0,
                       bytes=2.0 * rows * d * dtype_bytes * mult)
    expert = (grouped_matmul_cost(rows, d, h, E, dtype_bytes=dtype_bytes)
              * 2 +                                         # gate + up
              grouped_matmul_cost(rows, h, d, E,
                                  dtype_bytes=dtype_bytes)) * mult
    a2a = SectionCost(flops=0.0,
                      bytes=2.0 * rows * d * dtype_bytes * mult)
    L = num_moe_layers
    return {"gating": gating * L, "sort": sort * L,
            "expert_matmul": expert * L, "a2a": a2a * L}


def rms_norm_cost(n, d, *, residual=False, train=False,
                  dtype_bytes=2) -> SectionCost:
    """(Residual-)RMSNorm over ``n`` rows of ``d``: ~4 VPU ops per
    element fwd (square, reduce, rsqrt-scale, weight mul; +1 for the
    fused residual add). Bytes are the fused kernel's streams — each
    input read once, each output written once (the residual variant
    reads x+res and writes y+r: four streams, not six — exactly the
    traffic the fusion saves vs an unfused add + norm). ``train``
    multiplies both by 3 (dh kernel + dw reduction ~ 2 fwd-equiv)."""
    ops = 5.0 if residual else 4.0
    streams = 4.0 if residual else 2.0
    c = SectionCost(
        flops=ops * n * d,
        bytes=float(dtype_bytes) * (streams * n * d + d))
    return c * 3 if train else c


def swiglu_cost(n, h, *, train=False, dtype_bytes=2) -> SectionCost:
    """Fused SwiGLU over ``n`` rows of ``h``: ~6 VPU ops per element
    fwd (sigmoid ~4 + 2 muls), 3 streams (gate, up in; out). The bwd
    kernel recomputes sigmoid and writes dgate/dup: ~2x fwd work over
    5 streams — folded into the x3 train multiplier like every
    estimator here."""
    c = SectionCost(flops=6.0 * n * h,
                    bytes=float(dtype_bytes) * 3.0 * n * h)
    return c * 3 if train else c


def fused_linear_ce_cost(n, d, v, *, train=False,
                         dtype_bytes=2) -> SectionCost:
    """Chunked fused linear+cross-entropy: the lm_head matmul
    ``[n, d] @ [d, v]`` dominates (2ndv FLOPs; softmax/gather work is
    O(nv) VPU ops on top). Bytes NEVER include an [n, v] logits tensor
    — that is the point of the op: h and w stream once, the residents
    are [n]-vectors plus one f32 [n, d] dh accumulator in backward.
    ``train`` multiplies by 3 (model-FLOPs convention; the backward's
    logits re-matmul is remat-class recompute and deliberately NOT
    counted — module docstring)."""
    c = SectionCost(
        flops=2.0 * n * d * v + 4.0 * n * v,
        bytes=float(dtype_bytes) * (n * d + d * v)
        + 4.0 * 4.0 * n)           # f32 lse/max/sum/target vectors
    return c * 3 if train else c


def mfu(flops, seconds, peak=None, device=None) -> float:
    """Model-FLOPs utilization: flops / seconds / peak."""
    if peak is None:
        peak = device_peaks(device).flops
    if not seconds or not peak:
        return 0.0
    return flops / seconds / peak


def roofline(flops, bytes_, peaks: Peaks | None = None,
             device=None) -> dict:
    """Classify a section against the chip roofline. Returns arithmetic
    intensity, the ridge point, the bound ('compute' | 'memory'), and
    the attainable FLOP/s ceiling at this intensity."""
    if peaks is None:
        peaks = device_peaks(device)
    if not bytes_:
        return {"intensity": float("inf"), "ridge": peaks.ridge,
                "bound": "compute", "attainable_flops_per_s": peaks.flops}
    intensity = flops / bytes_
    bound = "compute" if intensity >= peaks.ridge else "memory"
    return {"intensity": intensity, "ridge": peaks.ridge, "bound": bound,
            "attainable_flops_per_s": min(peaks.flops,
                                          peaks.hbm_bw * intensity)}
