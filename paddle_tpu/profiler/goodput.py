"""Training goodput ledger — where did the wall clock go, across
restarts?

A preemptible fleet's real throughput is not step time: it is the
fraction of END-TO-END wall time spent inside productive compiled
steps, after subtracting input-wait, checkpoint saves, emergency
saves, restart gaps, resume resharding, and recompilation. This
module partitions wall time into exactly those categories and
PERSISTS the ledger (atomically) across ``PADDLE_RESTART_ROUND``\\ s,
so a run that was preempted three times still reports one honest
end-to-end goodput number.

Definitions (docs/observability.md):

- ``wall_s``   — sum over rounds of (round end − round start), plus
  the restart gaps BETWEEN rounds (the time the job owned resources
  or was waiting to again — a preempted hour is lost goodput).
- ``lost_<cat>_s`` — attributed non-productive time per category:
  ``input_wait`` (prefetcher starvation), ``checkpoint_save``
  (periodic saves), ``emergency_save`` (preemption drain+commit),
  ``restart`` (gap between a round ending and the next starting),
  ``reshard`` (resume-time checkpoint load + cross-mesh reshard),
  ``recompile`` (XLA compilation, discovery runs included).
- ``productive_s`` = ``wall_s`` − Σ lost — everything left is the
  compiled step stream actually advancing training.
- ``goodput_frac`` = ``productive_s / wall_s``.

Categories are attributed, not inferred: the fit loop measures each
directly (``ledger.measure("checkpoint_save")``), so a category the
loop never enters reads exactly 0. ``hapi.Model.fit`` maintains a
ledger automatically (in-memory always; persisted to
``<save_dir>/goodput.json`` when checkpointing is configured) and
``bench.py`` reports ``obs_goodput_frac`` / ``obs_lost_*`` from it.
"""

from __future__ import annotations

import contextlib
import json
import os
import time

from . import metrics as _metrics
from .trace import _atomic_json_dump

__all__ = ["GoodputLedger", "CATEGORIES", "LEDGER_SCHEMA",
           "set_current", "get_current"]

LEDGER_SCHEMA = "paddle_tpu.goodput/1"

#: the lost-time partition (see module docstring)
CATEGORIES = ("input_wait", "checkpoint_save", "emergency_save",
              "restart", "reshard", "recompile")

_metrics.declare("goodput/frac", "gauge",
                 "productive wall-time fraction across all restart "
                 "rounds (productive_s / wall_s)")
_metrics.declare("goodput/wall_s", "gauge",
                 "end-to-end wall seconds accounted by the ledger, "
                 "restart gaps included")
_metrics.declare("goodput/lost_s", "gauge",
                 "total non-productive seconds (sum of the lost "
                 "categories)")


class GoodputLedger:
    """Wall-time partition for one logical training run, spanning
    restart rounds (module docstring). ``path=None`` keeps the ledger
    in memory (no cross-round continuity); with a path, construction
    loads any previous rounds' ledger and books the gap since the last
    round was alive as ``restart`` time."""

    def __init__(self, path=None, round_=None, load=True):
        self.path = os.fspath(path) if path is not None else None
        self.round = int(os.environ.get("PADDLE_RESTART_ROUND", "0")) \
            if round_ is None else int(round_)
        self._rounds: dict[str, dict] = {}
        self._lost = {c: 0.0 for c in CATEGORIES}
        self._t_start = time.time()
        self._mono0 = time.monotonic()
        self._frozen = None     # (t_end, wall_s) pinned by close()
        # load=False: a deliberately FRESH run into a reused save_dir
        # (fit(resume=False)) must not inherit a stale ledger — the
        # days since its last round would read as restart loss
        if load and self.path is not None and os.path.exists(self.path):
            self._load_previous()

    def _load_previous(self):
        try:
            with open(self.path, encoding="utf-8") as f:
                doc = json.load(f)
            if doc.get("schema") != LEDGER_SCHEMA:
                raise ValueError(f"unknown ledger schema "
                                 f"{doc.get('schema')!r}")
            self._rounds = {k: v for k, v in doc.get("rounds", {}).items()
                            if k != str(self.round)}
        except (OSError, ValueError, KeyError) as e:
            # a torn/corrupt ledger must never sink a training run —
            # start a fresh one and say so
            import warnings
            warnings.warn(f"goodput ledger at {self.path} unreadable "
                          f"({e!r}); starting fresh")
            self._rounds = {}
        # NOTE: inter-round restart gaps are NOT booked here — they are
        # derived in summary() from the persisted t_start/t_end chain,
        # so they land in wall_s AND lost_restart_s consistently and a
        # re-load can never double count them.

    # -- attribution -------------------------------------------------------

    def add(self, category, seconds):
        if category not in self._lost:
            raise ValueError(f"unknown goodput category {category!r}; "
                             f"one of {CATEGORIES}")
        if seconds > 0:
            self._lost[category] += float(seconds)

    @contextlib.contextmanager
    def measure(self, category):
        """Time a block into a lost category."""
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self.add(category, time.perf_counter() - t0)

    # -- summary / persistence ---------------------------------------------

    def close(self):
        """Freeze the round's wall clock at end-of-run. ``summary()``
        and ``bench_keys()`` read the LIVE clock until then — a caller
        inspecting the ledger an hour after fit returned would
        otherwise see that idle hour booked as productive time.
        Idempotent; attribution (``add``) still lands after close."""
        if self._frozen is None:
            self._frozen = (time.time(),
                            time.monotonic() - self._mono0)

    def _this_round(self) -> dict:
        if self._frozen is not None:
            t_end, wall = self._frozen
        else:
            t_end = time.time()
            wall = time.monotonic() - self._mono0
        return {"t_start": round(self._t_start, 3),
                "t_end": round(t_end, 3),
                "wall_s": round(wall, 6),
                "lost": {c: round(v, 6)
                         for c, v in self._lost.items()}}

    def summary(self) -> dict:
        """Aggregate across every recorded round + the live one.
        Inter-round restart gaps (t_start[i+1] − t_end[i], the time no
        process was alive to measure) are derived from the persisted
        timestamps and added to BOTH wall and lost_restart, so the
        partition stays self-consistent. ``goodput_frac`` is clamped
        to [0, 1]: attribution overlap (e.g. a checkpoint save that
        also waited on input) must never report negative productive
        time."""
        rounds = dict(self._rounds)
        rounds[str(self.round)] = self._this_round()
        wall = sum(v.get("wall_s", 0.0) for v in rounds.values())
        lost = {c: 0.0 for c in CATEGORIES}
        for v in rounds.values():
            for c, s in v.get("lost", {}).items():
                if c in lost:
                    lost[c] += s
        # restart gaps between consecutive rounds, by wall-clock chain
        spans = sorted((v["t_start"], v["t_end"])
                       for v in rounds.values()
                       if isinstance(v.get("t_start"), (int, float))
                       and isinstance(v.get("t_end"), (int, float)))
        for (_, prev_end), (nxt_start, _) in zip(spans, spans[1:]):
            gap = nxt_start - prev_end
            if gap > 0:
                wall += gap
                lost["restart"] += gap
        total_lost = sum(lost.values())
        productive = max(0.0, wall - total_lost)
        frac = productive / wall if wall > 0 else 1.0
        out = {"wall_s": round(wall, 6),
               "productive_s": round(productive, 6),
               "lost_s": round(total_lost, 6),
               "goodput_frac": round(min(frac, 1.0), 6),
               "rounds": len(rounds),
               "round": self.round}
        for c in CATEGORIES:
            out[f"lost_{c}_s"] = round(lost[c], 6)
        reg = _metrics.get_registry()
        reg.gauge("goodput/frac").set(out["goodput_frac"])
        reg.gauge("goodput/wall_s").set(out["wall_s"])
        reg.gauge("goodput/lost_s").set(out["lost_s"])
        return out

    def bench_keys(self) -> dict:
        """The BENCH-record projection (BASELINE.md ``obs_*`` keys)."""
        s = self.summary()
        out = {"obs_goodput_frac": s["goodput_frac"],
               "obs_wall_s": round(s["wall_s"], 3)}
        for c in CATEGORIES:
            out[f"obs_lost_{c}_s"] = round(s[f"lost_{c}_s"], 3)
        return out

    def persist(self) -> str | None:
        """Atomically write the ledger (all rounds, this one current).
        Safe to call repeatedly — each epoch boundary, after an
        emergency save, and at exit all persist; the file on disk is
        always a complete document."""
        if self.path is None:
            return None
        rounds = dict(self._rounds)
        rounds[str(self.round)] = self._this_round()
        return _atomic_json_dump({"schema": LEDGER_SCHEMA,
                                  "rounds": rounds}, self.path)


# -- process-wide current ledger (ISSUE 13) ---------------------------------
# /statusz wants "the goodput summary" without threading a ledger handle
# through the serving stack; Model.fit registers its ledger here and
# leaves it registered after the run (the ledger is close()d, so its
# wall clock is frozen) — the exposition layer reads the live or most
# recent run, and None renders as an absent section. The next fit
# replaces it.

_current: GoodputLedger | None = None


def set_current(ledger: GoodputLedger | None):
    """Register (or clear, with None) the process's live ledger."""
    global _current
    _current = ledger
    return ledger


def get_current() -> GoodputLedger | None:
    return _current
