"""Stall flight recorder — a diagnosable artifact instead of a timeout.

A hung collective, a stalled serving scheduler, or a SIGKILL'd trainer
used to leave nothing but a dead process. This module keeps a
**lock-free ring buffer** of the last N runtime events (scheduler
turns, collective entries, checkpoint phases, preemption notices) and,
when something goes wrong, atomically dumps a **debug bundle**:

- the ring (ordered, seq-numbered events),
- every live thread's stack trace (``sys._current_frames`` — the stuck
  thread's frames are exactly the diagnosis),
- a metrics-registry snapshot (docs/observability.md),
- reason / timestamp / pid / ``PADDLE_RESTART_ROUND`` provenance.

Dump triggers:

- :class:`Watchdog` — a daemon thread armed around a should-progress
  region (serving run loop, elastic heartbeat); ``beat()`` marks
  progress, a gap past ``timeout_s`` dumps (once per stall episode).
- the crash hook (:func:`install_crash_hook`) — any uncaught exception
  (``Preempted`` included, via the elastic excepthook) dumps before
  the interpreter dies.
- **periodic persistence** (``persist_every``) — every Nth recorded
  event refreshes the on-disk bundle, so even a SIGKILL (which gives
  no thread a chance to run) leaves a complete, atomically-written
  bundle describing the process moments before death. Dumps are
  atomic (tmp + fsync + rename), so the bundle on disk is ALWAYS a
  complete JSON document — never torn (FaultInjector-tested).

Recording is wait-free for concurrent writers in CPython: a shared
``itertools.count`` hands out slot sequence numbers (``next()`` is a
single C call, atomic under the GIL) and each writer stores into its
own slot — no lock on the hot path, ~1µs per event. When no recorder
is installed, :func:`record_event` is a None check.

Stdlib-only: importable from signal handlers, excepthooks and the
serving hot loop without jax import weight.
"""

from __future__ import annotations

import itertools
import os
import sys
import threading
import time
import traceback

from . import metrics as _metrics
from .trace import _atomic_json_dump

__all__ = ["FlightRecorder", "Watchdog", "install", "uninstall",
           "get_recorder", "record_event", "beat", "install_crash_hook",
           "BUNDLE_SCHEMA"]

BUNDLE_SCHEMA = "paddle_tpu.flight_recorder/1"
BUNDLE_NAME = "flight_bundle.json"

_metrics.declare("obs/ring_events", "counter",
                 "events recorded into the flight-recorder ring "
                 "(scheduler turns, collective entries, checkpoint "
                 "phases)")
_metrics.declare("obs/bundle_dumps", "counter",
                 "flight-recorder debug bundles written (stall, crash, "
                 "periodic persistence)")
_metrics.declare("obs/stalls_detected", "counter",
                 "watchdog no-progress detections that produced a "
                 "bundle")


class FlightRecorder:
    """Fixed-capacity ring of recent runtime events + atomic bundle
    dumps (module docstring). ``registry`` defaults to the process-wide
    metrics registry so bundles carry the full gauge state."""

    def __init__(self, capacity=512, bundle_dir=None, registry=None,
                 persist_every=0, persist_min_interval_s=0.0,
                 keep_incidents=8):
        self.capacity = int(capacity)
        if self.capacity < 1:
            raise ValueError("ring capacity must be >= 1")
        self.bundle_dir = bundle_dir
        self.registry = registry
        self.persist_every = int(persist_every)
        self.persist_min_interval_s = float(persist_min_interval_s)
        self.keep_incidents = int(keep_incidents)
        self._slots: list = [None] * self.capacity
        self._seq = itertools.count()       # atomic under the GIL
        #: a live ServingFleet registers its FederatedRegistry here
        #: for the duration of run() (ISSUE 13): bundles dumped while
        #: a fleet is live — a replica-death post-mortem — then carry
        #: the FLEET-WIDE snapshot (every sibling's counters, replica-
        #: labeled), not just the local process registry
        self.fleet_registry = None
        self._last_persist = 0.0
        self._in_dump = threading.local()
        # serializes whole dumps across threads (watchdog vs periodic
        # persist vs crash hook): two writers sharing one tmp path
        # could otherwise interleave and publish a torn bundle
        self._dump_lock = threading.Lock()
        self.dumps = 0
        self.last_bundle_path = None

    # -- recording (hot path) ----------------------------------------------

    def record(self, kind, **fields):
        """Store one event. Wait-free: no lock; each writer owns the
        slot its sequence number maps to."""
        seq = next(self._seq)
        self._slots[seq % self.capacity] = (seq, time.time(), kind,
                                            fields)
        if self.persist_every and (seq + 1) % self.persist_every == 0:
            now = time.monotonic()
            if now - self._last_persist >= self.persist_min_interval_s:
                self._last_persist = now
                try:
                    self.dump("periodic")
                except OSError:
                    pass    # persistence is best-effort; never unwind
                            # the instrumented path over a full disk
        return seq

    def events(self):
        """The ring's current contents, oldest first. A snapshot taken
        while writers race may miss the newest few slots — acceptable
        for a flight recorder; ordering among returned events is exact
        (seq-sorted)."""
        items = [s for s in list(self._slots) if s is not None]
        items.sort(key=lambda s: s[0])
        return [{"seq": s[0], "t": round(s[1], 6), "kind": s[2],
                 **s[3]} for s in items]

    # -- dumping -----------------------------------------------------------

    def _thread_stacks(self):
        names = {t.ident: t.name for t in threading.enumerate()}
        stacks = {}
        for tid, frame in sys._current_frames().items():
            label = f"{names.get(tid, 'unknown')} ({tid})"
            stacks[label] = traceback.format_stack(frame)
        return stacks

    def bundle(self, reason) -> dict:
        reg = self.fleet_registry or self.registry \
            or _metrics.get_registry()
        try:
            metrics = reg.snapshot()
        except Exception:  # noqa: BLE001 — a half-torn-down fleet's
            # federated read must not cost us the rest of the bundle
            metrics = {}
            if reg is not self.registry:
                try:
                    metrics = (self.registry
                               or _metrics.get_registry()).snapshot()
                except Exception:  # noqa: BLE001
                    pass
        return {
            "schema": BUNDLE_SCHEMA,
            "reason": str(reason),
            "ts": time.time(),
            "pid": os.getpid(),
            "restart_round": int(os.environ.get("PADDLE_RESTART_ROUND",
                                                "0")),
            "events": self.events(),
            "threads": self._thread_stacks(),
            "metrics": metrics,
        }

    def incidents(self):
        """The preserved incident bundle filenames (newest last) —
        the /statusz incident list."""
        if self.bundle_dir is None:
            return []
        try:
            names = [f for f in os.listdir(self.bundle_dir)
                     if f.startswith("flight_incident_")
                     and f.endswith(".json")]
        except OSError:
            return []
        try:
            names.sort(key=lambda f: int(
                f[len("flight_incident_"):-len(".json")]))
        except ValueError:
            names.sort()
        return names

    def dump(self, reason, path=None) -> str | None:
        """Atomically write the debug bundle; returns its path (None
        when no destination is configured). Dumps are serialized
        across threads and reentrancy-guarded (a crash inside the dump
        path cannot recurse through the crash hook back into dump).
        INCIDENT dumps — any reason other than ``"periodic"`` — are
        additionally preserved as ``flight_incident_<n>.json``
        (newest ``keep_incidents`` kept), so a later periodic persist
        can never overwrite the stall/crash post-mortem this module
        exists to capture."""
        if getattr(self._in_dump, "active", False):
            return None
        if path is None:
            if self.bundle_dir is None:
                return None
            path = os.path.join(self.bundle_dir, BUNDLE_NAME)
        periodic = reason == "periodic"
        # periodic persists are opportunistic: if another thread is
        # mid-dump, skip instead of blocking the instrumented hot path
        if not self._dump_lock.acquire(blocking=not periodic):
            return None
        self._in_dump.active = True
        try:
            doc = self.bundle(reason)
            _atomic_json_dump(doc, path)
            self.dumps += 1
            if not periodic and self.keep_incidents > 0 \
                    and self.bundle_dir is not None:
                self._keep_incident(doc)
        finally:
            self._in_dump.active = False
            self._dump_lock.release()
        self.last_bundle_path = path
        reg = self.registry or _metrics.get_registry()
        reg.counter("obs/bundle_dumps").inc()
        return path

    def _keep_incident(self, doc):
        """Preserve an incident bundle under its own name and prune to
        the newest ``keep_incidents`` (best-effort: preservation must
        never fail the primary dump)."""
        try:
            inc = os.path.join(self.bundle_dir,
                               f"flight_incident_{self.dumps}.json")
            _atomic_json_dump(doc, inc)
            old = [f for f in os.listdir(self.bundle_dir)
                   if f.startswith("flight_incident_")
                   and f.endswith(".json")]
            old.sort(key=lambda f: int(f[len("flight_incident_"):
                                        -len(".json")]))
            for f in old[:-self.keep_incidents]:
                os.remove(os.path.join(self.bundle_dir, f))
        except (OSError, ValueError):
            pass


class Watchdog:
    """Daemon thread that dumps a bundle when an armed should-progress
    region stops beating. One dump per stall episode: progress resuming
    re-arms it."""

    def __init__(self, recorder, timeout_s=30.0, poll_s=None):
        self.recorder = recorder
        self.timeout_s = float(timeout_s)
        self.poll_s = float(poll_s) if poll_s is not None \
            else max(self.timeout_s / 4.0, 0.01)
        self._last_beat = time.monotonic()
        self._armed = threading.Event()
        self._stop = threading.Event()
        self._dumped_for_episode = False
        self._what = ""
        self._owner = None
        self.stall_dumps = 0
        self._thread = threading.Thread(target=self._run,
                                        name="obs-watchdog", daemon=True)
        self._thread.start()

    def arm(self, what=""):
        """Enter a should-progress region (e.g. a serving run loop).
        Returns an owner token: a ``beat``/``disarm`` carrying a
        DIFFERENT component's token is ignored, so a healthy fit loop
        beating cannot mask a stalled serving engine (and a finishing
        component cannot disarm someone else's region). One armed
        region per watchdog; a later arm takes ownership."""
        token = object()
        self._owner = token
        self._what = what
        self._last_beat = time.monotonic()
        self._dumped_for_episode = False
        self._armed.set()
        return token

    def disarm(self, token=None):
        if token is not None and token is not self._owner:
            return                      # not this component's region
        self._armed.clear()
        self._owner = None

    def beat(self, token=None):
        """Mark progress. ``token=None`` (direct single-component use)
        always counts; a stale token from a component that no longer
        owns the armed region does not."""
        if token is not None and token is not self._owner:
            return
        self._last_beat = time.monotonic()
        self._dumped_for_episode = False

    def stop(self):
        self._stop.set()
        self._armed.clear()
        self._thread.join(timeout=5.0)

    def _run(self):
        while not self._stop.is_set():
            if self._stop.wait(self.poll_s):
                return
            if not self._armed.is_set() or self._dumped_for_episode:
                continue
            gap = time.monotonic() - self._last_beat
            if gap > self.timeout_s:
                self._dumped_for_episode = True
                self.stall_dumps += 1
                reg = self.recorder.registry or _metrics.get_registry()
                reg.counter("obs/stalls_detected").inc()
                try:
                    self.recorder.dump(
                        f"stall: no progress for {gap:.2f}s "
                        f"(timeout {self.timeout_s}s"
                        + (f"; {self._what}" if self._what else "")
                        + ")")
                except OSError:
                    pass


# -- process-wide installation ---------------------------------------------

_RECORDER: FlightRecorder | None = None
_WATCHDOG: Watchdog | None = None


def install(recorder=None, watchdog_timeout_s=None, **kw) -> FlightRecorder:
    """Install ``recorder`` (or build one from ``**kw``) as the
    process-wide flight recorder; optionally start a watchdog. The
    instrumented call sites (serving scheduler, checkpoint phases,
    collectives) feed it through :func:`record_event`."""
    global _RECORDER, _WATCHDOG
    if recorder is None:
        recorder = FlightRecorder(**kw)
    _RECORDER = recorder
    if watchdog_timeout_s is not None:
        if _WATCHDOG is not None:
            _WATCHDOG.stop()
        _WATCHDOG = Watchdog(recorder, timeout_s=watchdog_timeout_s)
    elif _WATCHDOG is not None:
        # re-install without a new watchdog: rebind the live watchdog
        # to the new recorder, or its stall dump would snapshot the
        # OLD ring (empty of everything recorded since) into the old
        # bundle_dir
        _WATCHDOG.recorder = recorder
    return recorder


def uninstall():
    global _RECORDER, _WATCHDOG
    if _WATCHDOG is not None:
        _WATCHDOG.stop()
    _RECORDER = None
    _WATCHDOG = None


def get_recorder() -> FlightRecorder | None:
    return _RECORDER


def get_watchdog() -> Watchdog | None:
    return _WATCHDOG


#: cached at import so the per-event hot path pays one lock (the
#: counter's own), not a registry dict lookup per ring event
_RING_EVENTS = _metrics.get_registry().counter("obs/ring_events")


def record_event(kind, **fields):
    """Record into the installed recorder; a None check when none is
    installed (the default) — instrumentation stays in production
    paths for free."""
    rec = _RECORDER
    if rec is None:
        return None
    _RING_EVENTS.inc()
    return rec.record(kind, **fields)


def beat(token=None):
    """Mark progress on the installed watchdog (no-op otherwise);
    pass the token from :func:`arm` so only the owning component's
    beats count."""
    wd = _WATCHDOG
    if wd is not None:
        wd.beat(token)


def arm(what=""):
    """Arm the installed watchdog around a should-progress region;
    returns the owner token. With no watchdog installed the token is
    an INERT object (not None): if a watchdog appears mid-region and
    another component arms it, this region's ``beat(token)`` /
    ``disarm(token)`` must read as foreign and be ignored — a
    token=None fallthrough would let them mask (or disarm) the other
    component's armed region."""
    wd = _WATCHDOG
    if wd is not None:
        return wd.arm(what)
    return object()


def disarm(token=None):
    wd = _WATCHDOG
    if wd is not None:
        wd.disarm(token)


def install_crash_hook():
    """Chain a ``sys.excepthook`` that dumps a bundle on ANY uncaught
    exception (reason carries the exception repr) before delegating to
    the previous hook. Idempotent; a no-op while no recorder is
    installed at crash time."""
    prev = sys.excepthook
    if getattr(prev, "_paddle_flight_recorder", False):
        return

    def hook(exc_type, exc, tb):
        rec = _RECORDER
        if rec is not None:
            try:
                rec.dump(f"crash: {exc_type.__name__}: {exc}")
            except Exception:  # noqa: BLE001 — the crash must still print
                pass
        prev(exc_type, exc, tb)

    hook._paddle_flight_recorder = True
    sys.excepthook = hook
