"""Structured trace layer — nestable spans, gauges, chrome-trace export.

The round-5 verdict's blocking finding was *evidence*: MoE step time was
60% unattributed, serving ran at 74% of its occupancy ceiling with no
gauge saying so, and perf cliffs (scan declines, dropless downgrades)
were silent. This module is the measurement substrate every perf PR
cites: host-side spans with wall time + optional device-sync points +
FLOPs/bytes annotations, counter gauges, and export to both the chrome
trace-event schema (load in Perfetto / chrome://tracing) and raw JSON.

Deliberately stdlib-only at import time (no jax): it is imported from
hot paths (``nn/scan.py``, ``inference/serving.py``, ``hapi``) and must
never add import weight or create cycles. jax is imported lazily inside
:func:`block_on` only when a span actually requests a device sync.

Design notes:

- A DISABLED tracer costs one attribute read per span — instrumentation
  stays in production code paths (the Paddle profiler contract:
  ``RecordEvent`` is free unless a profiler is recording).
- Spans are exception-safe: the event is recorded (with an ``error``
  arg) even when the body raises, so a trace of a crashed step still
  shows where the time went.
- Exports are ATOMIC (tmp file + ``os.replace``): a crash or ENOSPC
  mid-export can never leave a torn, half-JSON trace file (same
  invariant as the checkpoint layer, docs/checkpoint_fault_tolerance.md).
"""

from __future__ import annotations

import json
import logging
import os
import threading
import time
from dataclasses import dataclass, field

__all__ = ["TraceEvent", "Tracer", "get_tracer", "trace_span",
           "block_on", "log_perf_event", "perf_logger", "epoch_summary",
           "RequestTraceLog", "get_trace_log"]

perf_logger = logging.getLogger("paddle_tpu.perf")

_US = 1e6


@dataclass
class TraceEvent:
    """One trace record in chrome trace-event vocabulary: ``ph="X"`` is
    a complete span (ts + dur), ``"C"`` a counter sample (gauges),
    ``"i"`` an instant marker (e.g. a device-sync point)."""

    name: str
    ph: str = "X"
    cat: str = "user"
    ts: float = 0.0          # microseconds since tracer epoch
    dur: float = 0.0         # microseconds (X events)
    tid: int = 0
    depth: int = 0
    args: dict = field(default_factory=dict)

    def to_chrome(self, pid: int) -> dict:
        ev = {"name": self.name, "ph": self.ph, "cat": self.cat,
              "ts": self.ts, "pid": pid, "tid": self.tid}
        if self.ph == "X":
            ev["dur"] = self.dur
        if self.ph == "i":
            ev["s"] = "t"            # thread-scoped instant
        if self.args:
            ev["args"] = self.args
        return ev


def block_on(value):
    """Device-sync point: block until ``value`` (Tensor / jax array /
    pytree / callable returning one) is computed. Returns the seconds
    spent blocked."""
    t0 = time.perf_counter()
    if callable(value):
        value = value()
    import jax
    leaves = []

    def _collect(v):
        if v is None:
            return
        if isinstance(v, (list, tuple)):
            for x in v:
                _collect(x)
            return
        data = getattr(v, "_data", v)       # Tensor -> jax array
        leaves.append(data)

    _collect(value)
    if leaves:
        jax.block_until_ready(leaves)
    return time.perf_counter() - t0


class _Span:
    """Context manager recording one X event. Exception-safe: records
    even when the body raises (annotating ``args['error']``)."""

    __slots__ = ("_tracer", "name", "cat", "args", "sync", "_t0", "_depth")

    def __init__(self, tracer, name, cat, sync, args):
        self._tracer = tracer
        self.name = name
        self.cat = cat
        self.sync = sync
        self.args = args

    def set_args(self, **kw):
        """Attach/override metadata mid-span (e.g. flops discovered
        after shapes are known)."""
        self.args.update(kw)
        return self

    def __enter__(self):
        tl = self._tracer._tl
        self._depth = getattr(tl, "depth", 0)
        tl.depth = self._depth + 1
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, exc_type, exc, tb):
        try:
            if self.sync is not None and exc_type is None:
                sync_s = block_on(self.sync)
                self.args.setdefault("sync_s", round(sync_s, 6))
            t1 = time.perf_counter()
            if exc_type is not None:
                self.args["error"] = f"{exc_type.__name__}: {exc}"
            self._tracer._record(TraceEvent(
                name=self.name, ph="X", cat=self.cat,
                ts=(self._t0 - self._tracer._epoch) * _US,
                dur=(t1 - self._t0) * _US,
                tid=threading.get_ident() & 0xFFFF, depth=self._depth,
                args=self.args))
        finally:
            self._tracer._tl.depth = self._depth
        return False                         # never swallow exceptions


class _NullSpan:
    """Shared no-op span for the disabled tracer (one object, no
    allocation per call)."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False

    def set_args(self, **kw):
        return self


_NULL_SPAN = _NullSpan()


class Tracer:
    """Process-wide structured trace recorder (see module docstring)."""

    def __init__(self, enabled: bool = False):
        self.enabled = bool(enabled)
        self.events: list[TraceEvent] = []
        self.options = None                 # ProfilerOptions when enabled
        self._lock = threading.Lock()
        self._tl = threading.local()
        self._epoch = time.perf_counter()

    # -- recording --------------------------------------------------------

    def _record(self, ev: TraceEvent):
        with self._lock:
            self.events.append(ev)

    def span(self, name, cat="user", sync=None, **args):
        """Nestable timed span. ``sync`` (Tensor/array/pytree/callable)
        inserts a device-sync point before the span closes, so the
        duration covers device work, not just dispatch. Extra kwargs
        become event args (``flops=``/``bytes=`` feed the per-section
        MFU/roofline summary, profiler.cost)."""
        if not self.enabled:
            return _NULL_SPAN
        return _Span(self, name, cat, sync, dict(args))

    def counter(self, name, value=None, cat="gauge", **values):
        """Record a gauge sample (chrome counter event)."""
        if not self.enabled:
            return
        args = dict(values)
        if value is not None:
            args.setdefault("value", value)
        self._record(TraceEvent(
            name=name, ph="C", cat=cat,
            ts=(time.perf_counter() - self._epoch) * _US,
            tid=threading.get_ident() & 0xFFFF, args=args))

    def instant(self, name, cat="marker", **args):
        if not self.enabled:
            return
        self._record(TraceEvent(
            name=name, ph="i", cat=cat,
            ts=(time.perf_counter() - self._epoch) * _US,
            tid=threading.get_ident() & 0xFFFF, args=dict(args)))

    def complete(self, name, t0, t1, cat="user", tid=None, **args):
        """Record a span RETROACTIVELY from ``perf_counter``
        timestamps: the serving request-lifecycle tracer reconstructs
        a request's queued/prefill/decode spans at completion time
        from stamps taken on the hot path (one float store each), so
        tracing a request costs nothing until it finishes. ``tid``
        gives the span its own track (e.g. the request id)."""
        if not self.enabled:
            return
        self._record(TraceEvent(
            name=name, ph="X", cat=cat,
            ts=(t0 - self._epoch) * _US,
            dur=max(0.0, t1 - t0) * _US,
            tid=(threading.get_ident() & 0xFFFF) if tid is None
            else int(tid),
            args=dict(args)))

    def device_sync(self, value, name="device_sync"):
        """Explicit sync point: blocks on ``value`` and records how long
        the host waited (the device-queue depth at this moment)."""
        if not self.enabled:
            return block_on(value)
        t0 = time.perf_counter()
        waited = block_on(value)
        self._record(TraceEvent(
            name=name, ph="X", cat="sync",
            ts=(t0 - self._epoch) * _US, dur=waited * _US,
            tid=threading.get_ident() & 0xFFFF,
            args={"waited_s": round(waited, 6)}))
        return waited

    def clear(self):
        with self._lock:
            self.events = []

    # -- summaries --------------------------------------------------------

    def section_summary(self, peak_flops=None):
        """Aggregate X events by name: count, total/mean ms, and — for
        spans annotated with ``flops``/``bytes`` — achieved FLOP/s, MFU
        against ``peak_flops`` and the roofline classification."""
        agg: dict[str, dict] = {}
        with self._lock:
            events = list(self.events)
        for ev in events:
            if ev.ph != "X":
                continue
            a = agg.setdefault(ev.name, {
                "count": 0, "total_ms": 0.0, "flops": 0.0, "bytes": 0.0})
            a["count"] += 1
            a["total_ms"] += ev.dur / 1e3
            a["flops"] += float(ev.args.get("flops", 0.0))
            a["bytes"] += float(ev.args.get("bytes", 0.0))
        for name, a in agg.items():
            a["mean_ms"] = a["total_ms"] / max(a["count"], 1)
            if a["flops"] and a["total_ms"]:
                a["flops_per_s"] = a["flops"] / (a["total_ms"] / 1e3)
                if peak_flops:
                    a["mfu"] = a["flops_per_s"] / peak_flops
            if a["flops"] and a["bytes"]:
                from .cost import roofline
                a["roofline"] = roofline(a["flops"], a["bytes"])
        return agg

    # -- export -----------------------------------------------------------

    def to_chrome_dict(self) -> dict:
        pid = os.getpid()
        with self._lock:
            events = list(self.events)
        return {"traceEvents": [ev.to_chrome(pid) for ev in events],
                "displayTimeUnit": "ms"}

    def export_chrome_trace(self, path) -> str:
        """Write the chrome trace-event JSON atomically; returns path."""
        return _atomic_json_dump(self.to_chrome_dict(), path)

    def export_json(self, path) -> str:
        """Raw structured export (events + section summary), atomic."""
        with self._lock:
            events = [{"name": e.name, "ph": e.ph, "cat": e.cat,
                       "ts_us": e.ts, "dur_us": e.dur, "depth": e.depth,
                       "args": e.args} for e in self.events]
        return _atomic_json_dump(
            {"events": events, "sections": self.section_summary()}, path)


def _atomic_write(path, write_fn) -> str:
    """tmp + fsync + os.replace: the export either fully exists or not
    at all (fault-injection-tested; a torn half-written export is worse
    than none). ``write_fn(f)`` serializes onto the open tmp file —
    the one atomic-write skeleton every profiler export (chrome trace,
    metrics JSON, Prometheus text, flight bundles) shares."""
    path = os.fspath(path)
    d = os.path.dirname(path)
    if d:
        os.makedirs(d, exist_ok=True)
    tmp = path + ".tmp"
    try:
        with open(tmp, "w", encoding="utf-8") as f:
            write_fn(f)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
    finally:
        if os.path.exists(tmp):
            try:
                os.remove(tmp)
            except OSError:
                pass
    return path


def _atomic_json_dump(obj, path) -> str:
    return _atomic_write(path, lambda f: json.dump(obj, f))


# -- completed request-trace log (ISSUE 13) ---------------------------------

class RequestTraceLog:
    """Bounded store of COMPLETED end-to-end request traces — the
    ``/statusz`` "N slowest recent traces" source.

    The chrome tracer captures everything while enabled, but a serving
    fleet needs "what were the slowest requests lately?" answerable at
    any moment without chrome tracing on. Feeders (the fleet at
    delivery; a standalone engine at completion) call :meth:`record`
    with one small summary dict per finished request — ``trace_id``,
    latency, the condensed hop list the request accumulated across
    replicas. Memory is fixed (a deque of ``capacity``), recording is
    O(1), reads copy under the lock — a scrape never observes a
    half-appended entry."""

    def __init__(self, capacity=256):
        from collections import deque
        self.capacity = int(capacity)
        self._entries = deque(maxlen=self.capacity)
        self._lock = threading.Lock()
        self.recorded = 0

    def record(self, trace: dict):
        with self._lock:
            self._entries.append(dict(trace))
            self.recorded += 1

    def recent(self, n=None):
        """Newest-last; the whole resident window by default."""
        with self._lock:
            out = list(self._entries)
        return out if n is None else out[-int(n):]

    def slowest(self, n=10, key="latency_ms"):
        """The N slowest resident traces, slowest first (ties broken
        by trace id for a stable /statusz render)."""
        with self._lock:
            out = list(self._entries)
        out.sort(key=lambda e: (-float(e.get(key, 0.0)),
                                str(e.get("trace_id"))))
        return out[:int(n)]

    def clear(self):
        with self._lock:
            self._entries.clear()


_trace_log = RequestTraceLog()


def get_trace_log() -> RequestTraceLog:
    """The process-wide completed-request trace log (always on; the
    fleet and standalone engines feed it, /statusz reads it)."""
    return _trace_log


_tracer = Tracer(enabled=False)


def get_tracer() -> Tracer:
    """The process-wide tracer (disabled until ``profiler.enable()`` /
    ``PADDLE_PROFILER_TRACE=1`` / ``FLAGS_enable_host_trace``)."""
    return _tracer


def trace_span(name, cat="user", sync=None, **args):
    """Module-level convenience: a span on the global tracer."""
    return _tracer.span(name, cat=cat, sync=sync, **args)


# -- perf event log --------------------------------------------------------

_logged_once: set = set()
_logged_lock = threading.Lock()


def log_perf_event(event: str, message: str, *, level=logging.INFO,
                   once_key=None, **args) -> bool:
    """Log a performance-relevant event at INFO (logger
    ``paddle_tpu.perf``) and mirror it into the trace as an instant
    marker. This is how silent perf cliffs become observable: scan-path
    declines, remat-dose drops, dropless downgrades all route here.

    ``once_key`` dedupes process-wide (the cliff fires every forward;
    the log should not). Returns True iff the line was emitted."""
    if once_key is not None:
        with _logged_lock:
            if once_key in _logged_once:
                return False
            _logged_once.add(once_key)
    perf_logger.log(level, "[%s] %s", event, message)
    _tracer.instant(event, cat="perf_event", message=message, **args)
    return True


def epoch_summary(epoch, steps, seconds, **metrics) -> dict:
    """Per-epoch training summary (hapi.Model.fit hook): logs one INFO
    line, emits gauges, and returns the summary dict."""
    avg_ms = seconds / max(steps, 1) * 1e3
    summary = {"epoch": int(epoch), "steps": int(steps),
               "epoch_s": round(seconds, 4),
               "avg_step_ms": round(avg_ms, 3),
               "steps_per_s": round(steps / seconds, 3) if seconds else 0.0}
    summary.update(metrics)
    perf_logger.info("[hapi/epoch] %s", json.dumps(summary, sort_keys=True))
    # registry gauge (docs/observability.md); the default registry
    # mirrors into the tracer while tracing is on, preserving the old
    # chrome-trace counter stream
    from .metrics import get_registry
    get_registry().gauge("hapi/avg_step_ms").set(
        summary["avg_step_ms"], epoch=int(epoch))
    return summary
