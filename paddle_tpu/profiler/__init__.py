"""``paddle.profiler`` (python/paddle/profiler/ parity, UNVERIFIED) —
grown into the perf observability subsystem.

Reference: host RecordEvent ranges + CUPTI device tracer → chrome trace
(SURVEY.md §5). TPU-native: ``jax.profiler`` captures host + device (TPU)
timelines into TensorBoard/Perfetto format; ``RecordEvent`` maps to
``jax.profiler.TraceAnnotation`` so user annotations appear in the same
trace. Summary tables come from jax's own profile session where available;
``profiler_result.save`` exports the trace dir.

On top of that capture surface, three structured layers (docs/
profiling.md):

- :mod:`.trace` — nestable ``trace_span()`` events with wall time,
  device-sync points, gauges, chrome-trace + JSON export;
- :mod:`.cost` — FLOPs/bytes accounting from static shapes, per-section
  MFU and roofline (compute- vs memory-bound) classification;
- :mod:`.breakdown` — the in-program section-ablation harness that
  attributes step time inside one compiled program (MoE gating / sort /
  a2a / expert-matmul; the evidence layer for every perf PR).

Enable via ``Profiler``/``enable()``, the ``PADDLE_PROFILER_TRACE=1``
env flag, or ``FLAGS_enable_host_trace``.
"""

from __future__ import annotations

import dataclasses
import os
import time

import jax

from ..framework.core import Tensor
from . import cost, trace  # noqa: F401 (public submodules)
from . import exposition, flight_recorder, goodput  # noqa: F401
from . import metrics, slo  # noqa: F401
from .breakdown import (StepBreakdown, ablation_breakdown,  # noqa: F401
                        moe_step_breakdown)
from .exposition import ObservabilityServer  # noqa: F401
from .flight_recorder import FlightRecorder, Watchdog  # noqa: F401
from .goodput import GoodputLedger  # noqa: F401
from .metrics import (Counter, FederatedRegistry, Gauge,  # noqa: F401
                      Histogram, MetricsRegistry, get_registry)
from .slo import SLORule, SLOTracker  # noqa: F401
from .trace import (Tracer, block_on, get_tracer,  # noqa: F401
                    log_perf_event, trace_span)

__all__ = ["Profiler", "RecordEvent", "ProfilerTarget", "ProfilerState",
           "make_scheduler", "export_chrome_tracing", "load_profiler_result",
           "SortedKeys", "SummaryView", "ProfilerOptions", "enable",
           "disable", "trace_span", "get_tracer", "Tracer", "block_on",
           "log_perf_event", "StepBreakdown", "ablation_breakdown",
           "moe_step_breakdown", "cost", "trace",
           "metrics", "flight_recorder", "goodput",
           "Counter", "Gauge", "Histogram", "MetricsRegistry",
           "get_registry", "FlightRecorder", "Watchdog", "GoodputLedger"]


def _env_bool(name, default=False):
    v = os.environ.get(name)
    if v is None:
        return default
    return v.lower() in ("1", "true", "yes", "on")


@dataclasses.dataclass
class ProfilerOptions:
    """Knob surface for the structured trace layer (the
    ``paddle.utils.profiler.ProfilerOptions`` shape, TPU-native fields).
    Every field has a ``PADDLE_PROFILER_*`` env twin so headless runs
    (bench.py, the elastic launcher) can flip tracing without code."""

    output_dir: str = "./profiler_log"          # PADDLE_PROFILER_LOG_DIR
    trace_enabled: bool = False                 # PADDLE_PROFILER_TRACE
    with_flops: bool = False                    # PADDLE_PROFILER_WITH_FLOPS
    sync_spans: bool = False                    # PADDLE_PROFILER_SYNC
    export_on_disable: bool = True

    @classmethod
    def from_env(cls) -> "ProfilerOptions":
        return cls(
            output_dir=os.environ.get("PADDLE_PROFILER_LOG_DIR",
                                      "./profiler_log"),
            trace_enabled=_env_bool("PADDLE_PROFILER_TRACE"),
            with_flops=_env_bool("PADDLE_PROFILER_WITH_FLOPS"),
            sync_spans=_env_bool("PADDLE_PROFILER_SYNC"))


def enable(options: ProfilerOptions | None = None) -> Tracer:
    """Turn the structured trace layer on process-wide."""
    tr = get_tracer()
    tr.options = options or ProfilerOptions.from_env()
    tr.enabled = True
    return tr


def disable(export: bool | None = None) -> str | None:
    """Turn tracing off; by default exports the chrome trace into
    ``options.output_dir`` if any events were recorded. Returns the
    export path (or None)."""
    tr = get_tracer()
    opts = tr.options or ProfilerOptions()
    tr.enabled = False
    path = None
    if (opts.export_on_disable if export is None else export) \
            and tr.events:
        path = tr.export_chrome_trace(
            os.path.join(opts.output_dir, "paddle_trace.json"))
    return path


def _env_trace_requested() -> bool:
    if _env_bool("PADDLE_PROFILER_TRACE"):
        return True
    # FLAGS_enable_host_trace=1 in the environment: define_flag ingests
    # the value but on_change only fires through set_flags, so honor
    # the env form here (the flag's contract says it is the same switch)
    try:
        from ..framework.flags import flag
        return bool(flag("FLAGS_enable_host_trace"))
    except Exception:
        return False


if _env_trace_requested():
    enable()  # env-flag surface: tracing from process start


class ProfilerTarget:
    CPU = "cpu"
    GPU = "gpu"
    CUSTOM_DEVICE = "custom"
    TPU = "tpu"


class ProfilerState:
    CLOSED = 0
    READY = 1
    RECORD = 2
    RECORD_AND_RETURN = 3


class SortedKeys:
    CPUTotal = 0
    CPUAvg = 1
    GPUTotal = 2


class SummaryView:
    DeviceView = 0
    OverView = 1
    ModelView = 2
    DistributedView = 3
    KernelView = 4
    OperatorView = 5
    MemoryView = 6


def make_scheduler(closed=0, ready=0, record=1, repeat=0, skip_first=0):
    """Return a step→state callable (paddle.profiler.make_scheduler)."""
    cycle = closed + ready + record

    def scheduler(step):
        if step < skip_first:
            return ProfilerState.CLOSED
        s = step - skip_first
        if repeat and s >= repeat * cycle:
            return ProfilerState.CLOSED
        pos = s % cycle if cycle else 0
        if pos < closed:
            return ProfilerState.CLOSED
        if pos < closed + ready:
            return ProfilerState.READY
        if pos == cycle - 1:
            return ProfilerState.RECORD_AND_RETURN
        return ProfilerState.RECORD
    return scheduler


def export_chrome_tracing(dir_name, worker_name=None):
    """Trace-ready handler directing the capture into ``dir_name``. The
    Profiler reads ``handler.log_dir`` at construction so the directory is
    set BEFORE recording starts (the jax trace is written at stop time)."""
    def handler(prof):
        prof._log_dir = dir_name
    handler.log_dir = dir_name
    return handler


def load_profiler_result(path):
    return path


class RecordEvent:
    """User range annotation; shows up in the jax/Perfetto trace AND —
    when the structured tracer is enabled — as a ``trace_span`` in the
    chrome-trace/JSON export."""

    def __init__(self, name, event_type=None):
        self.name = name
        self._ann = None
        self._span = None
        self.begin_ts = None

    def begin(self):
        self._ann = jax.profiler.TraceAnnotation(self.name)
        self._ann.__enter__()
        tr = get_tracer()
        if tr.enabled:
            self._span = tr.span(self.name, cat="record_event")
            self._span.__enter__()
        self.begin_ts = time.perf_counter()

    def end(self):
        if self._span is not None:
            self._span.__exit__(None, None, None)
            self._span = None
        if self._ann is not None:
            self._ann.__exit__(None, None, None)
            self._ann = None

    def __enter__(self):
        self.begin()
        return self

    def __exit__(self, *exc):
        self.end()
        return False


class Profiler:
    def __init__(self, targets=None, scheduler=None, on_trace_ready=None,
                 record_shapes=False, profile_memory=False, timer_only=False,
                 emit_nvtx=False, custom_device_types=None, with_flops=False,
                 options: ProfilerOptions | None = None):
        self._scheduler = scheduler if callable(scheduler) else None
        if isinstance(scheduler, (tuple, list)):
            lo, hi = scheduler
            self._scheduler = lambda step: (
                ProfilerState.RECORD if lo <= step < hi
                else ProfilerState.CLOSED)
        self._on_trace_ready = on_trace_ready
        self._log_dir = os.environ.get("PADDLE_PROFILER_LOG_DIR",
                                       "./profiler_log")
        if on_trace_ready is not None and hasattr(on_trace_ready,
                                                  "log_dir"):
            self._log_dir = on_trace_ready.log_dir
        self._step = 0
        self._recording = False
        self._timer_only = timer_only
        self._step_times = []
        self._last = None
        self._with_flops = with_flops
        self._options = options
        if options is not None and getattr(options, "output_dir", None):
            self._log_dir = options.output_dir

    def start(self):
        if self._with_flops or self._options is not None:
            # structured trace layer rides along: spans/gauges recorded
            # while this Profiler is live land in the chrome export.
            # Save the global tracer's prior state — a sub-region
            # Profiler must not stomp a whole-process tracing session
            # (PADDLE_PROFILER_TRACE=1).
            tr = get_tracer()
            self._prev_trace_state = (tr.enabled, tr.options)
            opts = self._options or ProfilerOptions(
                output_dir=self._log_dir, with_flops=self._with_flops)
            enable(opts)
        self._last = time.perf_counter()
        self._maybe_transition()

    def stop(self):
        if self._recording:
            jax.profiler.stop_trace()
            self._recording = False
            if self._on_trace_ready:
                self._on_trace_ready(self)
        if self._with_flops or self._options is not None:
            prev_enabled, prev_options = getattr(
                self, "_prev_trace_state", (False, None))
            if prev_enabled:
                # outer tracing session continues: restore its options,
                # keep recording, export nothing early
                tr = get_tracer()
                tr.options = prev_options
            else:
                disable()

    def step(self, num_samples=None):
        now = time.perf_counter()
        if self._last is not None:
            self._step_times.append(now - self._last)
        self._last = now
        self._step += 1
        self._maybe_transition()

    def _maybe_transition(self):
        if self._timer_only or self._scheduler is None:
            return
        state = self._scheduler(self._step)
        want = state in (ProfilerState.RECORD,
                         ProfilerState.RECORD_AND_RETURN)
        if want and not self._recording:
            os.makedirs(self._log_dir, exist_ok=True)
            jax.profiler.start_trace(self._log_dir)
            self._recording = True
        elif not want and self._recording:
            jax.profiler.stop_trace()
            self._recording = False
            if self._on_trace_ready:
                self._on_trace_ready(self)

    def summary(self, sorted_by=None, op_detail=True, thread_sep=False,
                time_unit="ms"):
        n = len(self._step_times)
        if not n:
            print("No steps recorded")
            return
        avg = sum(self._step_times) / n
        print(f"steps: {n}  avg step time: {avg * 1e3:.3f} ms  "
              f"throughput: {1.0 / avg:.2f} steps/s")
        sections = get_tracer().section_summary(
            peak_flops=cost.device_peaks().flops)
        for name, a in sorted(sections.items(),
                              key=lambda kv: -kv[1]["total_ms"]):
            mfu_s = f"  MFU {a['mfu'] * 100:.1f}%" if "mfu" in a else ""
            bound = a.get("roofline", {}).get("bound", "")
            print(f"  {name}: {a['count']}x  total {a['total_ms']:.2f} ms"
                  f"  mean {a['mean_ms']:.3f} ms{mfu_s}"
                  f"{'  [' + bound + '-bound]' if bound else ''}")

    def export(self, path=None, format="json"):
        tr = get_tracer()
        if path is not None and tr.events:
            tr.export_chrome_trace(path)
            return path
        return self._log_dir

    def __enter__(self):
        self.start()
        return self

    def __exit__(self, *exc):
        self.stop()
        return False
