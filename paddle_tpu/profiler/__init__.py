"""``paddle.profiler`` (python/paddle/profiler/ parity, UNVERIFIED).

Reference: host RecordEvent ranges + CUPTI device tracer → chrome trace
(SURVEY.md §5). TPU-native: ``jax.profiler`` captures host + device (TPU)
timelines into TensorBoard/Perfetto format; ``RecordEvent`` maps to
``jax.profiler.TraceAnnotation`` so user annotations appear in the same
trace. Summary tables come from jax's own profile session where available;
``profiler_result.save`` exports the trace dir."""

from __future__ import annotations

import os
import time

import jax

from ..framework.core import Tensor

__all__ = ["Profiler", "RecordEvent", "ProfilerTarget", "ProfilerState",
           "make_scheduler", "export_chrome_tracing", "load_profiler_result",
           "SortedKeys", "SummaryView"]


class ProfilerTarget:
    CPU = "cpu"
    GPU = "gpu"
    CUSTOM_DEVICE = "custom"
    TPU = "tpu"


class ProfilerState:
    CLOSED = 0
    READY = 1
    RECORD = 2
    RECORD_AND_RETURN = 3


class SortedKeys:
    CPUTotal = 0
    CPUAvg = 1
    GPUTotal = 2


class SummaryView:
    DeviceView = 0
    OverView = 1
    ModelView = 2
    DistributedView = 3
    KernelView = 4
    OperatorView = 5
    MemoryView = 6


def make_scheduler(closed=0, ready=0, record=1, repeat=0, skip_first=0):
    """Return a step→state callable (paddle.profiler.make_scheduler)."""
    cycle = closed + ready + record

    def scheduler(step):
        if step < skip_first:
            return ProfilerState.CLOSED
        s = step - skip_first
        if repeat and s >= repeat * cycle:
            return ProfilerState.CLOSED
        pos = s % cycle if cycle else 0
        if pos < closed:
            return ProfilerState.CLOSED
        if pos < closed + ready:
            return ProfilerState.READY
        if pos == cycle - 1:
            return ProfilerState.RECORD_AND_RETURN
        return ProfilerState.RECORD
    return scheduler


def export_chrome_tracing(dir_name, worker_name=None):
    """Trace-ready handler directing the capture into ``dir_name``. The
    Profiler reads ``handler.log_dir`` at construction so the directory is
    set BEFORE recording starts (the jax trace is written at stop time)."""
    def handler(prof):
        prof._log_dir = dir_name
    handler.log_dir = dir_name
    return handler


def load_profiler_result(path):
    return path


class RecordEvent:
    """User range annotation; shows up in the jax/Perfetto trace."""

    def __init__(self, name, event_type=None):
        self.name = name
        self._ann = None
        self.begin_ts = None

    def begin(self):
        self._ann = jax.profiler.TraceAnnotation(self.name)
        self._ann.__enter__()
        self.begin_ts = time.perf_counter()

    def end(self):
        if self._ann is not None:
            self._ann.__exit__(None, None, None)
            self._ann = None

    def __enter__(self):
        self.begin()
        return self

    def __exit__(self, *exc):
        self.end()
        return False


class Profiler:
    def __init__(self, targets=None, scheduler=None, on_trace_ready=None,
                 record_shapes=False, profile_memory=False, timer_only=False,
                 emit_nvtx=False, custom_device_types=None, with_flops=False):
        self._scheduler = scheduler if callable(scheduler) else None
        if isinstance(scheduler, (tuple, list)):
            lo, hi = scheduler
            self._scheduler = lambda step: (
                ProfilerState.RECORD if lo <= step < hi
                else ProfilerState.CLOSED)
        self._on_trace_ready = on_trace_ready
        self._log_dir = os.environ.get("PADDLE_PROFILER_LOG_DIR",
                                       "./profiler_log")
        if on_trace_ready is not None and hasattr(on_trace_ready,
                                                  "log_dir"):
            self._log_dir = on_trace_ready.log_dir
        self._step = 0
        self._recording = False
        self._timer_only = timer_only
        self._step_times = []
        self._last = None

    def start(self):
        self._last = time.perf_counter()
        self._maybe_transition()

    def stop(self):
        if self._recording:
            jax.profiler.stop_trace()
            self._recording = False
            if self._on_trace_ready:
                self._on_trace_ready(self)

    def step(self, num_samples=None):
        now = time.perf_counter()
        if self._last is not None:
            self._step_times.append(now - self._last)
        self._last = now
        self._step += 1
        self._maybe_transition()

    def _maybe_transition(self):
        if self._timer_only or self._scheduler is None:
            return
        state = self._scheduler(self._step)
        want = state in (ProfilerState.RECORD,
                         ProfilerState.RECORD_AND_RETURN)
        if want and not self._recording:
            os.makedirs(self._log_dir, exist_ok=True)
            jax.profiler.start_trace(self._log_dir)
            self._recording = True
        elif not want and self._recording:
            jax.profiler.stop_trace()
            self._recording = False
            if self._on_trace_ready:
                self._on_trace_ready(self)

    def summary(self, sorted_by=None, op_detail=True, thread_sep=False,
                time_unit="ms"):
        n = len(self._step_times)
        if not n:
            print("No steps recorded")
            return
        avg = sum(self._step_times) / n
        print(f"steps: {n}  avg step time: {avg * 1e3:.3f} ms  "
              f"throughput: {1.0 / avg:.2f} steps/s")

    def export(self, path=None, format="json"):
        return self._log_dir

    def __enter__(self):
        self.start()
        return self

    def __exit__(self, *exc):
        self.stop()
        return False
