"""Per-tenant SLO accounting with error-budget burn-rate alerts
(ISSUE 13).

The serving stack can *shed* against an SLO (PR 10's admission
control) but could not *account* against one: nothing answered "are we
meeting our TTFT SLO per tenant?" or noticed a tenant's error budget
burning down. This module is that ledger:

- :class:`SLORule` — one DECLARATIVE objective: a request-level
  predicate (``kind``: first token within ``threshold_ms`` /
  end-to-end latency within ``threshold_ms`` / typed-error-free
  completion), an attainment ``target`` (e.g. 0.99 = "99% of requests
  good"), and a partition (``by``: request attributes, default
  ``tenant``) — every distinct label value gets its own window.
- :class:`SLOTracker` — rolling attainment windows. ``record(req)``
  books one finished :class:`~paddle_tpu.inference.serving.ServedRequest`
  into every rule, prunes events older than ``window_s``, and
  evaluates the **burn rate**: ``miss_frac / (1 - target)`` over the
  window — burn 1.0 means the error budget is being consumed exactly
  at the sustainable rate, ``burn_alert`` (default 2.0) times that
  fires an alert record (and clears it when the burn drops back
  below). Alerts surface three ways: the ``alerts()`` list (live),
  ``alert_history`` (bounded), and the ``slo/*`` metric family —
  attainment + burn-rate gauges and event/miss/alert counters, labeled
  ``{rule=...,tenant=...}`` — which a
  :class:`~.metrics.FederatedRegistry`-backed ``/metrics`` endpoint
  exposes and ``/statusz`` renders via :meth:`summary`.

Deterministic and clock-injectable (``now_fn``): the burn-rate tests
drive synthetic timelines without sleeping. Stdlib-only; O(1) memory
per (rule, label) — windows prune as they record, and the label space
is bounded by ``max_labels`` (an adversarial tenant-id stream must
not grow the tracker without limit; overflow labels are folded into
``"_overflow"``).
"""

from __future__ import annotations

import threading
import time
from collections import deque
from dataclasses import dataclass, field

from . import metrics as _metrics

__all__ = ["SLORule", "SLOTracker"]

_metrics.declare("slo/events", "counter",
                 "finished requests booked into an SLO rule's rolling "
                 "window (labeled rule/tenant)")
_metrics.declare("slo/misses", "counter",
                 "requests that violated their SLO rule's objective "
                 "(labeled rule/tenant)")
_metrics.declare("slo/attainment", "gauge",
                 "good-request fraction over the rule's rolling window "
                 "(labeled rule/tenant; 1.0 while empty)")
_metrics.declare("slo/burn_rate", "gauge",
                 "error-budget burn rate over the rolling window: "
                 "miss_frac / (1 - target); 1.0 = budget consumed "
                 "exactly at the sustainable rate (labeled "
                 "rule/tenant)")
_metrics.declare("slo/alerts_fired", "counter",
                 "burn-rate alert activations (burn crossed the "
                 "rule's alert threshold; labeled rule/tenant)")
_metrics.declare("slo/alerts_active", "gauge",
                 "burn-rate alerts currently firing across all rules "
                 "and labels")


@dataclass(frozen=True)
class SLORule:
    """One declarative objective (module docstring).

    ``kind``:

    - ``"ttft"`` — good iff a first token landed within
      ``threshold_ms`` of arrival (no first token at all = miss);
    - ``"e2e"`` — good iff the request finished within
      ``threshold_ms`` of arrival;
    - ``"success"`` — good iff it completed without a typed error
      (``threshold_ms`` unused).

    ``by`` names request attributes whose values partition the
    accounting (default per-tenant; ``("tenant", "priority")`` gives
    per-tenant-per-priority windows). ``min_events`` keeps a
    nearly-empty window from alerting off one unlucky request.
    """

    name: str
    kind: str = "ttft"
    threshold_ms: float | None = None
    target: float = 0.99
    by: tuple = ("tenant",)
    window_s: float = 300.0
    burn_alert: float = 2.0
    min_events: int = 10
    #: client-initiated cancellations are VOLUNTARY: by default they
    #: are excluded from the window entirely (neither good nor miss) —
    #: a tenant abandoning requests must not burn its own error budget
    #: into a false alert. Set True to count them as misses.
    count_cancelled: bool = False

    def __post_init__(self):
        if self.kind not in ("ttft", "e2e", "success"):
            raise ValueError(f"unknown SLO kind {self.kind!r}")
        if self.kind in ("ttft", "e2e") and self.threshold_ms is None:
            raise ValueError(
                f"SLO rule {self.name!r}: kind {self.kind!r} needs "
                "threshold_ms")
        if not 0.0 < self.target < 1.0:
            raise ValueError("target must be in (0, 1) — an SLO of "
                             "1.0 has no error budget to burn")

    def excludes(self, req) -> bool:
        """True when the request should not be booked at all (a
        voluntary client cancellation, unless ``count_cancelled``)."""
        return not self.count_cancelled and \
            getattr(req, "finish_reason", None) == "cancelled"

    def good(self, req) -> bool:
        """The request-level predicate (arrival-relative, the clock
        the engines already stamp)."""
        if self.kind == "success":
            return req.error is None
        if self.kind == "ttft":
            if not req.t_first:
                return False
            return (req.t_first - req.t_arrive) * 1e3 \
                <= self.threshold_ms
        end = req.t_done or req.t_first
        if not end:
            return False
        return (end - req.t_arrive) * 1e3 <= self.threshold_ms

    def labels_of(self, req) -> tuple:
        return tuple(str(getattr(req, f, None)) for f in self.by)


class _Window:
    """One (rule, label) rolling window: a deque of (t, good)."""

    __slots__ = ("events", "good")

    def __init__(self):
        self.events: deque = deque()
        self.good = 0

    def add(self, t, ok):
        self.events.append((t, ok))
        if ok:
            self.good += 1

    def prune(self, horizon):
        ev = self.events
        while ev and ev[0][0] < horizon:
            _, ok = ev.popleft()
            if ok:
                self.good -= 1


@dataclass
class _AlertState:
    active: bool = False
    fired: int = 0
    record: dict | None = None


class SLOTracker:
    """Rolling SLO accounting over a rule set (module docstring).
    ``registry`` receives the ``slo/*`` metric family (a fleet passes
    its federated registry so ``/metrics`` carries attainment);
    defaults to the process-wide registry."""

    def __init__(self, rules, registry=None, now_fn=None,
                 max_labels=256, alert_history=64):
        self.rules = list(rules)
        names = [r.name for r in self.rules]
        if len(set(names)) != len(names):
            raise ValueError(f"duplicate SLO rule names: {names}")
        self.registry = registry if registry is not None \
            else _metrics.get_registry()
        self._now = now_fn if now_fn is not None else time.perf_counter
        self.max_labels = int(max_labels)
        self._lock = threading.Lock()
        #: (rule_name, labels) -> _Window
        self._windows: dict[tuple, _Window] = {}
        self._alerts: dict[tuple, _AlertState] = {}
        self.alert_history: deque = deque(maxlen=int(alert_history))

    # -- label plumbing ----------------------------------------------------

    def _window(self, rule, labels):
        key = (rule.name, labels)
        w = self._windows.get(key)
        if w is None:
            if len(self._windows) >= self.max_labels \
                    and key not in self._windows:
                labels = ("_overflow",) * len(rule.by)
                key = (rule.name, labels)
                w = self._windows.get(key)
                if w is not None:
                    return key, w
            w = _Window()
            self._windows[key] = w
        return key, w

    def _label_kv(self, rule, labels):
        kv = {"rule": rule.name}
        kv.update(zip(rule.by, labels))
        return kv

    # -- recording ---------------------------------------------------------

    def record(self, req):
        """Book one FINISHED request into every rule; returns the
        alert records newly fired by this event."""
        now = self._now()
        fired = []
        with self._lock:
            for rule in self.rules:
                if rule.excludes(req):
                    continue
                key, w = self._window(rule, rule.labels_of(req))
                ok = rule.good(req)
                w.add(now, ok)
                w.prune(now - rule.window_s)
                kv = self._label_kv(rule, key[1])
                self.registry.counter("slo/events").labels(**kv).inc()
                if not ok:
                    self.registry.counter("slo/misses") \
                        .labels(**kv).inc()
                a = self._evaluate(rule, key, w, now, kv)
                if a is not None:
                    fired.append(a)
            self.registry.gauge("slo/alerts_active").set(
                sum(1 for st in self._alerts.values() if st.active))
        return fired

    def _evaluate(self, rule, key, w, now, kv):
        """Attainment + burn under the lock; returns a NEWLY-fired
        alert record or None. Gauges are updated on every event, so a
        scrape between requests reads current state."""
        n = len(w.events)
        attain = (w.good / n) if n else 1.0
        budget = 1.0 - rule.target
        burn = ((1.0 - attain) / budget) if n else 0.0
        self.registry.gauge("slo/attainment").labels(**kv).set(
            round(attain, 6))
        self.registry.gauge("slo/burn_rate").labels(**kv).set(
            round(burn, 6))
        st = self._alerts.setdefault(key, _AlertState())
        alerting = n >= rule.min_events and burn >= rule.burn_alert
        if alerting and not st.active:
            st.active = True
            st.fired += 1
            st.record = {
                "rule": rule.name, "kind": rule.kind,
                "labels": dict(zip(rule.by, key[1])),
                "burn_rate": round(burn, 4),
                "attainment": round(attain, 6),
                "target": rule.target, "events": n,
                "window_s": rule.window_s, "t": now,
            }
            self.alert_history.append(dict(st.record))
            self.registry.counter("slo/alerts_fired") \
                .labels(**kv).inc()
            return dict(st.record)
        if not alerting and st.active:
            st.active = False
            st.record = None
        elif alerting:
            # refresh the live record so /statusz shows current burn
            st.record.update(burn_rate=round(burn, 4),
                             attainment=round(attain, 6),
                             events=n, t=now)
        return None

    # -- read side ---------------------------------------------------------

    def _refresh_locked(self, now):
        """Prune every window to its rule's horizon and CLEAR alerts
        whose burn has aged out (caller holds the lock). Without this
        a tenant that stopped sending traffic after a bad minute
        would page forever: record() never runs again for its label,
        so only the read side can observe the window emptying."""
        rules = {r.name: r for r in self.rules}
        for (rn, lv), w in self._windows.items():
            rule = rules[rn]
            before = len(w.events)
            w.prune(now - rule.window_s)
            n = len(w.events)
            attain = (w.good / n) if n else 1.0
            burn = ((1.0 - attain) / (1.0 - rule.target)) if n else 0.0
            if n != before:
                # the window changed shape with no record() to rewrite
                # the gauges: a scrape must read the SAME attainment
                # /statusz reports ("1.0 while empty"), not the last
                # pre-silence value frozen forever
                kv = self._label_kv(rule, lv)
                self.registry.gauge("slo/attainment").labels(**kv) \
                    .set(round(attain, 6))
                self.registry.gauge("slo/burn_rate").labels(**kv) \
                    .set(round(burn, 6))
            st = self._alerts.get((rn, lv))
            if st is not None and st.active and (
                    n < rule.min_events or burn < rule.burn_alert):
                st.active = False
                st.record = None
        self.registry.gauge("slo/alerts_active").set(
            sum(1 for st in self._alerts.values() if st.active))

    def refresh(self):
        """Re-evaluate every window against the clock NOW: prune aged
        events, rewrite the attainment/burn gauges, clear expired
        alerts. ``summary()``/``alerts()`` do this implicitly; the
        exposition layer calls it before rendering ``/metrics`` so a
        Prometheus-only scraper (no /statusz) never reads a burn rate
        frozen from before a tenant went silent."""
        with self._lock:
            self._refresh_locked(self._now())

    def alerts(self):
        """Currently-ACTIVE alert records — re-evaluated against the
        rolling window at read time, so an alert self-resolves once
        its misses age out even if that (rule, tenant) never records
        another event."""
        with self._lock:
            self._refresh_locked(self._now())
            return [dict(st.record) for st in self._alerts.values()
                    if st.active and st.record is not None]

    def attainment(self, rule_name, **labels):
        """Current attainment for one (rule, label) window; 1.0 while
        empty/unknown (no traffic = no misses)."""
        with self._lock:
            for (rn, lv), w in self._windows.items():
                rule = next(r for r in self.rules if r.name == rn)
                if rn == rule_name and \
                        dict(zip(rule.by, lv)) == {
                            k: str(v) for k, v in labels.items()}:
                    n = len(w.events)
                    return (w.good / n) if n else 1.0
        return 1.0

    def summary(self) -> dict:
        """The /statusz + bench projection: per rule, per label —
        events/attainment/burn/alerting — plus the overall worst
        attainment and total alerts fired (the BENCH
        ``obs_slo_attainment`` / ``slo_alerts`` keys)."""
        with self._lock:
            self._refresh_locked(self._now())
            rules_by_name = {r.name: r for r in self.rules}
            out_rules = {}
            worst = 1.0
            total_fired = 0
            for (rn, lv), w in sorted(self._windows.items()):
                rule = rules_by_name[rn]
                n = len(w.events)
                attain = (w.good / n) if n else 1.0
                budget = 1.0 - rule.target
                burn = ((1.0 - attain) / budget) if n else 0.0
                st = self._alerts.get((rn, lv))
                slot = out_rules.setdefault(rn, {
                    "kind": rule.kind, "target": rule.target,
                    "threshold_ms": rule.threshold_ms,
                    "window_s": rule.window_s, "labels": {}})
                slot["labels"][",".join(lv)] = {
                    "events": n, "attainment": round(attain, 6),
                    "burn_rate": round(burn, 4),
                    "alerting": bool(st and st.active),
                    "alerts_fired": st.fired if st else 0,
                }
                if n:
                    worst = min(worst, attain)
                total_fired += st.fired if st else 0
            return {
                "rules": out_rules,
                "worst_attainment": round(worst, 6),
                "alerts_fired": total_fired,
                "alerts_active": [dict(st.record)
                                  for st in self._alerts.values()
                                  if st.active
                                  and st.record is not None],
            }
