"""paddle.text — NLP utilities and datasets.

Reference surface: upstream ``python/paddle/text/`` (UNVERIFIED; see
SURVEY.md provenance warning): ViterbiDecoder / viterbi_decode plus classic
datasets (Imdb, Imikolov, UCIHousing, ...). Datasets are cache-only in this
zero-egress environment with a ``backend='generate'`` synthetic fallback,
like paddle.vision.datasets here.
"""

from __future__ import annotations

import os
import tarfile

import jax
import jax.numpy as jnp
import numpy as np

from ..framework.core import Tensor, apply
from ..io import Dataset
from ..nn.layer.layers import Layer
from ..ops.common import as_tensor
from ..utils.download import WEIGHTS_HOME

__all__ = ["viterbi_decode", "ViterbiDecoder", "UCIHousing", "Imdb",
           "Imikolov"]


def viterbi_decode(potentials, transition_params, lengths,
                   include_bos_eos_tag=True, name=None):
    """Viterbi decoding of a linear-chain CRF (paddle.text.viterbi_decode).

    potentials: [B, T, N] unary emissions; transition_params: [N, N];
    lengths: [B]. With ``include_bos_eos_tag`` the LAST TWO of the N tags
    are the BOS/EOS tags (upstream paddle convention): the start scores add
    ``trans[N-2, :]`` and the final scores add ``trans[:, N-1]``.
    Returns (scores [B], paths [B, T]). The DP runs as a ``lax.scan`` over
    time — one fused compiled loop, argmax backtrace scanned in reverse.
    """
    def fn(emit, trans, lens):
        B, T, N = emit.shape
        if trans.shape[-1] != N:
            raise ValueError(
                f"transition_params must be [{N}, {N}] to match the "
                f"emission tag count, got {tuple(trans.shape)}; with "
                "include_bos_eos_tag the BOS/EOS tags are the last two of "
                "the N tags, not extra rows")
        tr = trans
        if include_bos_eos_tag:
            bos, eos = N - 2, N - 1
            start = trans[bos, :][None, :] + emit[:, 0]
        else:
            start = emit[:, 0]
        t_steps = jnp.arange(1, T)

        def step(carry, t):
            alpha = carry  # [B, N]
            scores = alpha[:, :, None] + tr[None]  # [B, N, N]
            best_prev = jnp.argmax(scores, axis=1)  # [B, N]
            best_score = jnp.max(scores, axis=1) + emit[:, t]
            # positions past the sequence end keep their alpha
            active = (t < lens)[:, None]
            alpha_new = jnp.where(active, best_score, alpha)
            bp = jnp.where(active, best_prev,
                           jnp.broadcast_to(jnp.arange(alpha.shape[1]),
                                            best_prev.shape))
            return alpha_new, bp

        alpha, bps = jax.lax.scan(step, start, t_steps)  # bps [T-1, B, N]
        if include_bos_eos_tag:
            alpha = alpha + trans[:, eos][None, :]
        scores = jnp.max(alpha, -1)
        last_tag = jnp.argmax(alpha, -1)  # [B]

        def back(carry, bp):
            tag = carry
            prev = jnp.take_along_axis(bp, tag[:, None], 1)[:, 0]
            # bp for step t maps tag_t -> tag_{t-1}; emit the predecessor so
            # the stacked ys are [tag_0 .. tag_{T-2}]
            return prev, prev

        _, path_prefix = jax.lax.scan(back, last_tag, bps, reverse=True)
        paths = jnp.concatenate(
            [path_prefix, last_tag[None]], 0).transpose(1, 0)  # [B, T]
        return scores, paths.astype(jnp.int64)

    return apply(fn, as_tensor(potentials), as_tensor(transition_params),
                 as_tensor(lengths), n_outputs=2, name="viterbi_decode",
                 differentiable=False)


class ViterbiDecoder(Layer):
    """Layer wrapper holding the transition matrix
    (paddle.text.ViterbiDecoder)."""

    def __init__(self, transitions, include_bos_eos_tag=True, name=None):
        super().__init__()
        self.transitions = as_tensor(transitions)
        self.include_bos_eos_tag = include_bos_eos_tag

    def forward(self, potentials, lengths):
        return viterbi_decode(potentials, self.transitions, lengths,
                              self.include_bos_eos_tag)


def _missing(name, path):
    raise RuntimeError(
        f"{name}: data file {path!r} not found and this environment has no "
        f"network access. Place the file there (or under {WEIGHTS_HOME}), "
        f"or pass backend='generate' for a synthetic offline split.")


class UCIHousing(Dataset):
    """Boston housing regression dataset (13 features -> price)."""

    def __init__(self, data_file=None, mode="train", download=True,
                 backend=None):
        assert mode in ("train", "test")
        if backend == "generate":
            rng = np.random.RandomState(0 if mode == "train" else 1)
            n = 400 if mode == "train" else 100
            x = rng.rand(n, 13).astype("float32")
            w = np.linspace(-1, 1, 13).astype("float32")
            y = (x @ w + 0.1 * rng.randn(n)).astype("float32")[:, None]
            self.data = [(x[i], y[i]) for i in range(n)]
            return
        data_file = data_file or os.path.join(WEIGHTS_HOME, "housing.data")
        if not os.path.exists(data_file):
            _missing("UCIHousing", data_file)
        raw = np.loadtxt(data_file).astype("float32")
        split = int(len(raw) * 0.8)
        part = raw[:split] if mode == "train" else raw[split:]
        feats = (part[:, :13] - raw[:, :13].mean(0)) / \
            (raw[:, :13].std(0) + 1e-8)
        self.data = [(feats[i], part[i, 13:14]) for i in range(len(part))]

    def __len__(self):
        return len(self.data)

    def __getitem__(self, idx):
        return self.data[idx]


class Imdb(Dataset):
    """IMDB movie-review sentiment dataset (aclImdb tar archive)."""

    def __init__(self, data_file=None, mode="train", cutoff=150,
                 download=True, backend=None):
        assert mode in ("train", "test")
        if backend == "generate":
            rng = np.random.RandomState(2 if mode == "train" else 3)
            n = 500 if mode == "train" else 100
            vocab = 200
            self.word_idx = {f"w{i}": i for i in range(vocab)}
            docs, labels = [], []
            for i in range(n):
                label = rng.randint(0, 2)
                # class-dependent token distribution so models can learn
                lo, hi = (0, vocab // 2) if label == 0 else (vocab // 2,
                                                             vocab)
                docs.append(rng.randint(lo, hi,
                                        rng.randint(5, 40)).astype("int64"))
                labels.append(label)
            self.docs, self.labels = docs, np.asarray(labels, "int64")
            return
        data_file = data_file or os.path.join(WEIGHTS_HOME,
                                              "aclImdb_v1.tar.gz")
        if not os.path.exists(data_file):
            _missing("Imdb", data_file)
        import re
        pat = re.compile(rf"(?:\./)?aclImdb/{mode}/(pos|neg)/.*\.txt$")
        freq: dict[str, int] = {}
        texts, labels = [], []
        with tarfile.open(data_file, "r:*") as tar:
            for m in tar.getmembers():
                match = pat.match(m.name)
                if not match:
                    continue
                body = tar.extractfile(m).read().decode(
                    "utf-8", errors="ignore").lower()
                toks = body.split()
                texts.append(toks)
                labels.append(1 if match.group(1) == "pos" else 0)
                for t in toks:
                    freq[t] = freq.get(t, 0) + 1
        words = sorted((w for w, c in freq.items() if c >= cutoff),
                       key=lambda w: -freq[w])
        self.word_idx = {w: i for i, w in enumerate(words)}
        oov = len(self.word_idx)
        self.docs = [np.asarray([self.word_idx.get(t, oov) for t in toks],
                                "int64") for toks in texts]
        self.labels = np.asarray(labels, "int64")

    def __len__(self):
        return len(self.docs)

    def __getitem__(self, idx):
        return self.docs[idx], self.labels[idx]


class Imikolov(Dataset):
    """PTB language-model n-gram dataset (imikolov simple-examples)."""

    def __init__(self, data_file=None, data_type="NGRAM", window_size=5,
                 mode="train", min_word_freq=50, download=True,
                 backend=None):
        assert mode in ("train", "test")
        if backend == "generate":
            rng = np.random.RandomState(4 if mode == "train" else 5)
            n, vocab = 1000, 100
            self.word_idx = {f"w{i}": i for i in range(vocab)}
            stream = rng.randint(0, vocab, n + window_size)
            self.grams = [stream[i:i + window_size].astype("int64")
                          for i in range(n)]
            return
        data_file = data_file or os.path.join(WEIGHTS_HOME,
                                              "simple-examples.tgz")
        if not os.path.exists(data_file):
            _missing("Imikolov", data_file)
        member = f"./simple-examples/data/ptb.{mode}.txt"
        with tarfile.open(data_file, "r:*") as tar:
            names = tar.getnames()
            name = member if member in names else member.lstrip("./")
            text = tar.extractfile(name).read().decode("utf-8")
        freq: dict[str, int] = {}
        sents = []
        for line in text.strip().split("\n"):
            toks = ["<s>"] + line.split() + ["<e>"]
            sents.append(toks)
            for t in toks:
                freq[t] = freq.get(t, 0) + 1
        words = sorted((w for w, c in freq.items()
                        if c >= min_word_freq or w in ("<s>", "<e>")),
                       key=lambda w: -freq[w])
        self.word_idx = {w: i for i, w in enumerate(words)}
        unk = len(self.word_idx)
        self.grams = []
        for toks in sents:
            ids = [self.word_idx.get(t, unk) for t in toks]
            for i in range(len(ids) - window_size + 1):
                self.grams.append(np.asarray(ids[i:i + window_size],
                                             "int64"))

    def __len__(self):
        return len(self.grams)

    def __getitem__(self, idx):
        g = self.grams[idx]
        return tuple(g[:-1]), g[-1]


class Conll05st(Dataset):
    """CoNLL-2005 semantic-role-labeling dataset. Offline-gated like the
    other text datasets: point ``data_file`` at the extracted corpus, or
    pass ``backend='generate'`` for a synthetic split (same item shape:
    token-id sequence + predicate index + SRL tag ids)."""

    def __init__(self, data_file=None, mode="train", download=True,
                 backend=None, vocab_size=800, n_tags=20):
        assert mode in ("train", "test")
        if backend == "generate":
            rng = np.random.RandomState(0 if mode == "train" else 1)
            n = 120 if mode == "train" else 30
            self.data = []
            for _ in range(n):
                ln = int(rng.randint(5, 25))
                toks = rng.randint(0, vocab_size, (ln,)).astype("int64")
                pred = int(rng.randint(0, ln))
                tags = rng.randint(0, n_tags, (ln,)).astype("int64")
                self.data.append((toks, pred, tags))
            return
        data_file = data_file or os.path.join(WEIGHTS_HOME, "conll05st")
        if not os.path.exists(data_file):
            _missing("Conll05st", data_file)
        raise NotImplementedError(
            "Conll05st: parsing a local corpus dump is not implemented; "
            "use backend='generate' for the synthetic split")

    def __len__(self):
        return len(self.data)

    def __getitem__(self, idx):
        return self.data[idx]


class Movielens(Dataset):
    """MovieLens ratings (user, movie, rating). Offline-gated; the
    ``ml-1m`` ratings.dat format is parsed when present."""

    def __init__(self, data_file=None, mode="train", download=True,
                 backend=None, test_ratio=0.1, rand_seed=0):
        assert mode in ("train", "test")
        if backend == "generate":
            rng = np.random.RandomState(0)
            n = 500
            users = rng.randint(0, 100, n).astype("int64")
            movies = rng.randint(0, 200, n).astype("int64")
            ratings = rng.randint(1, 6, n).astype("float32")
            split = int(n * (1 - test_ratio))
            sl = slice(0, split) if mode == "train" else slice(split, n)
            self.data = list(zip(users[sl], movies[sl], ratings[sl]))
            return
        data_file = data_file or os.path.join(WEIGHTS_HOME,
                                              "ml-1m/ratings.dat")
        if not os.path.exists(data_file):
            _missing("Movielens", data_file)
        rows = []
        with open(data_file) as f:
            for line in f:
                parts = line.strip().split("::")
                if len(parts) >= 3:
                    rows.append((np.int64(parts[0]), np.int64(parts[1]),
                                 np.float32(parts[2])))
        rng = np.random.RandomState(rand_seed)
        rng.shuffle(rows)
        split = int(len(rows) * (1 - test_ratio))
        self.data = rows[:split] if mode == "train" else rows[split:]

    def __len__(self):
        return len(self.data)

    def __getitem__(self, idx):
        return self.data[idx]


class _WMTBase(Dataset):
    """Shared WMT14/WMT16 shape: (src ids, tgt ids, tgt_next ids)."""

    def __init__(self, name, data_file, mode, backend, src_vocab,
                 tgt_vocab):
        assert mode in ("train", "test", "dev")
        if backend == "generate":
            rng = np.random.RandomState({"train": 0, "dev": 1,
                                         "test": 2}[mode])
            n = {"train": 200, "dev": 40, "test": 40}[mode]
            self.data = []
            for _ in range(n):
                sl = int(rng.randint(4, 20))
                tl = int(rng.randint(4, 20))
                src = rng.randint(2, src_vocab, (sl,)).astype("int64")
                tgt = rng.randint(2, tgt_vocab, (tl,)).astype("int64")
                self.data.append((src, np.concatenate([[0], tgt]),
                                  np.concatenate([tgt, [1]])))
            return
        if data_file is None or not os.path.exists(data_file):
            _missing(name, data_file or os.path.join(WEIGHTS_HOME, name))
        self.data = []
        with open(data_file) as f:
            for line in f:
                cols = line.rstrip("\n").split("\t")
                if len(cols) != 2:
                    continue
                src = np.asarray([int(t) for t in cols[0].split()],
                                 "int64")
                tgt = np.asarray([int(t) for t in cols[1].split()],
                                 "int64")
                self.data.append((src, np.concatenate([[0], tgt]),
                                  np.concatenate([tgt, [1]])))

    def __len__(self):
        return len(self.data)

    def __getitem__(self, idx):
        return self.data[idx]


class WMT14(_WMTBase):
    """WMT'14 EN-DE translation pairs (pre-tokenized id TSV when local)."""

    def __init__(self, data_file=None, mode="train", dict_size=30000,
                 download=True, backend=None):
        super().__init__("WMT14", data_file, mode, backend, dict_size,
                         dict_size)


class WMT16(_WMTBase):
    """WMT'16 EN-DE translation pairs (pre-tokenized id TSV when local)."""

    def __init__(self, data_file=None, mode="train", src_dict_size=30000,
                 trg_dict_size=30000, lang="en", download=True,
                 backend=None):
        super().__init__("WMT16", data_file, mode, backend, src_dict_size,
                         trg_dict_size)


__all__ += ["Conll05st", "Movielens", "WMT14", "WMT16"]


from . import datasets  # noqa: E402,F401 — upstream import-path parity
__all__ += ["datasets"]
