"""``paddle.text.datasets`` — dataset classes namespace (upstream keeps
the dataset classes in a submodule; they live in ``paddle_tpu.text``
directly, re-exported here for import-path parity)."""

from . import __all__ as _text_all  # noqa: F401
from . import (UCIHousing, Imdb, Imikolov, Movielens, Conll05st,  # noqa
               WMT14, WMT16)

__all__ = ["UCIHousing", "Imdb", "Imikolov", "Movielens", "Conll05st",
           "WMT14", "WMT16"]
