"""``paddle.signal`` — STFT / iSTFT (python/paddle/signal.py parity,
UNVERIFIED). Framed via gather + jnp.fft so the whole transform is one
XLA program (differentiable; oracle = overlap-add reconstruction)."""

from __future__ import annotations

import numpy as np

import jax.numpy as jnp

from ..framework.core import Tensor, apply
from ..ops.common import as_tensor

__all__ = ["stft", "istft", "frame", "overlap_add"]


def _window_array(window, n_fft, dtype):
    if window is None:
        return jnp.ones((n_fft,), dtype)
    w = window.jax() if isinstance(window, Tensor) else jnp.asarray(window)
    if w.shape[0] != n_fft:
        raise ValueError(f"window length {w.shape[0]} != n_fft {n_fft}")
    return w.astype(dtype)


def frame(x, frame_length, hop_length, axis=-1, name=None):
    """Split the last axis into overlapping frames:
    [..., N] -> [..., frame_length, num_frames] (paddle layout)."""
    if axis != -1:
        raise NotImplementedError("frame: axis=-1 only")

    def fn(a):
        n = a.shape[-1]
        num = 1 + (n - frame_length) // hop_length
        starts = jnp.arange(num) * hop_length
        idx = starts[:, None] + jnp.arange(frame_length)[None, :]
        return jnp.moveaxis(a[..., idx], -2, -1)  # [..., flen, num]
    return apply(fn, as_tensor(x), name="frame")


def overlap_add(x, hop_length, axis=-1, name=None):
    """Inverse of frame: [..., frame_length, num_frames] -> [..., N]."""

    def fn(a):
        flen, num = a.shape[-2], a.shape[-1]
        n = (num - 1) * hop_length + flen
        out = jnp.zeros(a.shape[:-2] + (n,), a.dtype)
        for i in range(num):  # static unroll; num is compile-time
            out = out.at[..., i * hop_length:i * hop_length + flen].add(
                a[..., i])
        return out
    return apply(fn, as_tensor(x), name="overlap_add")


def stft(x, n_fft, hop_length=None, win_length=None, window=None,
         center=True, pad_mode="reflect", normalized=False,
         onesided=True, name=None):
    """[B, N] (or [N]) -> complex [B, n_fft//2+1, num_frames]
    (onesided) — paddle.signal.stft semantics."""
    hop = hop_length or n_fft // 4
    wl = win_length or n_fft

    def fn(a):
        squeeze = a.ndim == 1
        if squeeze:
            a = a[None]
        w = _window_array(window, wl, a.dtype)
        if wl < n_fft:  # center-pad window to n_fft
            lp = (n_fft - wl) // 2
            w = jnp.pad(w, (lp, n_fft - wl - lp))
        if center:
            pad = n_fft // 2
            a = jnp.pad(a, ((0, 0), (pad, pad)), mode=pad_mode)
        n = a.shape[-1]
        num = 1 + (n - n_fft) // hop
        starts = jnp.arange(num) * hop
        idx = starts[:, None] + jnp.arange(n_fft)[None, :]
        frames = a[:, idx] * w[None, None, :]  # [B, num, n_fft]
        spec = (jnp.fft.rfft(frames, axis=-1) if onesided
                else jnp.fft.fft(frames, axis=-1))
        if normalized:
            spec = spec / jnp.sqrt(jnp.asarray(n_fft, spec.real.dtype))
        out = jnp.swapaxes(spec, -2, -1)  # [B, freq, num]
        return out[0] if squeeze else out
    return apply(fn, as_tensor(x), name="stft")


def istft(x, n_fft, hop_length=None, win_length=None, window=None,
          center=True, normalized=False, onesided=True, length=None,
          return_complex=False, name=None):
    """Inverse STFT with window-envelope normalization (NOLA)."""
    hop = hop_length or n_fft // 4
    wl = win_length or n_fft

    def fn(a):
        squeeze = a.ndim == 2
        if squeeze:
            a = a[None]
        spec = jnp.swapaxes(a, -2, -1)  # [B, num, freq]
        if normalized:
            spec = spec * jnp.sqrt(jnp.asarray(n_fft, spec.real.dtype))
        frames = (jnp.fft.irfft(spec, n=n_fft, axis=-1) if onesided
                  else jnp.fft.ifft(spec, axis=-1).real)
        w = _window_array(window, wl, frames.dtype)
        if wl < n_fft:
            lp = (n_fft - wl) // 2
            w = jnp.pad(w, (lp, n_fft - wl - lp))
        frames = frames * w[None, None, :]
        num = frames.shape[1]
        n = (num - 1) * hop + n_fft
        out = jnp.zeros(frames.shape[:1] + (n,), frames.dtype)
        env = jnp.zeros((n,), frames.dtype)
        for i in range(num):
            sl = slice(i * hop, i * hop + n_fft)
            out = out.at[:, sl].add(frames[:, i])
            env = env.at[sl].add(w * w)
        out = out / jnp.maximum(env, 1e-11)[None, :]
        if center:
            pad = n_fft // 2
            out = out[:, pad:n - pad]
        if length is not None:
            out = out[:, :length]
        return out[0] if squeeze else out
    return apply(fn, as_tensor(x), name="istft")
