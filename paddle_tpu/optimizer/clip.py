"""Gradient clipping (python/paddle/nn/clip.py parity, UNVERIFIED).

``ClipGradByGlobalNorm`` is distributed-aware in the reference (norms
allreduced across mp/pp/sharding groups); on TPU the same computation inside
a compiled region gets its psum inserted by GSPMD automatically, and the
hybrid-parallel optimizer wrapper adds explicit psums where running under
shard_map."""

from __future__ import annotations

import jax.numpy as jnp

from ..framework.core import Tensor, no_grad

__all__ = ["ClipGradBase", "ClipGradByGlobalNorm", "ClipGradByNorm",
           "ClipGradByValue", "clip_grad_norm_", "clip_grad_value_"]


class ClipGradBase:
    def __call__(self, params_grads):
        raise NotImplementedError


class ClipGradByGlobalNorm(ClipGradBase):
    def __init__(self, clip_norm=1.0, group_name="default_group",
                 auto_skip_clip=False):
        self.clip_norm = float(clip_norm)
        self.group_name = group_name
        self.auto_skip_clip = auto_skip_clip

    def __call__(self, params_grads):
        grads = [g for _, g in params_grads if g is not None]
        if not grads:
            return params_grads
        sq = sum(jnp.sum(jnp.square(g._data.astype(jnp.float32)))
                 for g in grads)
        global_norm = jnp.sqrt(sq)
        scale = self.clip_norm / jnp.maximum(global_norm, self.clip_norm)
        out = []
        for p, g in params_grads:
            if g is None:
                out.append((p, g))
            else:
                out.append((p, Tensor((g._data.astype(jnp.float32)
                                       * scale).astype(g.dtype))))
        return out


class ClipGradByNorm(ClipGradBase):
    def __init__(self, clip_norm=1.0):
        self.clip_norm = float(clip_norm)

    def __call__(self, params_grads):
        out = []
        for p, g in params_grads:
            if g is None:
                out.append((p, g))
                continue
            norm = jnp.sqrt(jnp.sum(jnp.square(
                g._data.astype(jnp.float32))))
            scale = self.clip_norm / jnp.maximum(norm, self.clip_norm)
            out.append((p, Tensor((g._data.astype(jnp.float32)
                                   * scale).astype(g.dtype))))
        return out


class ClipGradByValue(ClipGradBase):
    def __init__(self, max=1.0, min=None):
        self.max = float(max)
        self.min = float(min) if min is not None else -self.max

    def __call__(self, params_grads):
        return [(p, Tensor(jnp.clip(g._data, self.min, self.max))
                 if g is not None else g)
                for p, g in params_grads]


def clip_grad_norm_(parameters, max_norm, norm_type=2.0,
                    error_if_nonfinite=False):
    if isinstance(parameters, Tensor):
        parameters = [parameters]
    grads = [p.grad for p in parameters if p.grad is not None]
    if not grads:
        return Tensor(jnp.zeros(()))
    if norm_type == float("inf"):
        total = jnp.max(jnp.stack([jnp.max(jnp.abs(g._data))
                                   for g in grads]))
    else:
        total = jnp.sum(jnp.stack(
            [jnp.sum(jnp.abs(g._data.astype(jnp.float32)) ** norm_type)
             for g in grads])) ** (1.0 / norm_type)
    clip_coef = jnp.minimum(max_norm / (total + 1e-6), 1.0)
    with no_grad():
        for p in parameters:
            if p.grad is not None:
                p.grad.set_data((p.grad._data.astype(jnp.float32)
                                 * clip_coef).astype(p.grad.dtype))
    return Tensor(total)


def clip_grad_value_(parameters, clip_value):
    if isinstance(parameters, Tensor):
        parameters = [parameters]
    with no_grad():
        for p in parameters:
            if p.grad is not None:
                p.grad.set_data(jnp.clip(p.grad._data, -clip_value,
                                         clip_value))
