from .optimizer import (Optimizer, SGD, Momentum, Adam, AdamW, Adamax,
                        Adagrad, Adadelta, RMSProp, Lamb, LBFGS)
from . import lr
from .clip import (ClipGradByGlobalNorm, ClipGradByNorm, ClipGradByValue,
                   clip_grad_norm_)

__all__ = ["Optimizer", "SGD", "Momentum", "Adam", "AdamW", "Adamax",
           "Adagrad", "Adadelta", "RMSProp", "Lamb", "LBFGS", "lr",
           "ClipGradByGlobalNorm", "ClipGradByNorm", "ClipGradByValue"]
