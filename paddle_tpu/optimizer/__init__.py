from .optimizer import (Optimizer, SGD, Momentum, Adam, AdamW, Adamax,
                        Adagrad, Adadelta, RMSProp, Lamb, LBFGS, Rprop,
                        ASGD, NAdam, RAdam)
from . import lr
from .clip import (ClipGradByGlobalNorm, ClipGradByNorm, ClipGradByValue,
                   clip_grad_norm_)

__all__ = ["Optimizer", "SGD", "Momentum", "Adam", "AdamW", "Adamax",
           "Adagrad", "Adadelta", "RMSProp", "Lamb", "LBFGS", "Rprop",
           "ASGD", "NAdam", "RAdam", "lr",
           "ClipGradByGlobalNorm", "ClipGradByNorm", "ClipGradByValue"]
