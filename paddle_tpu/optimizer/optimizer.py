"""Optimizer base + the standard family
(python/paddle/optimizer/ parity, UNVERIFIED).

Update math runs as jax ops on the wrapped arrays; under
``paddle_tpu.jit.to_static`` the whole step (grads → clip → update) traces
into the compiled program, which is where XLA fuses it into the fused
multi-tensor-apply the reference implements by hand (SURVEY.md §3.2 step 4).
Accumulators are persistable Tensors so the functionalizer captures them.
Master weights: when a parameter is low-precision (bf16/fp16), Adam-family
optimizers keep an fp32 master copy (paddle `multi_precision`)."""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..framework.core import Tensor, Parameter, no_grad, is_floating
from .lr import LRScheduler
from .clip import ClipGradBase

__all__ = ["Optimizer", "SGD", "Momentum", "Adam", "AdamW", "Adamax",
           "Adagrad", "Adadelta", "RMSProp", "Lamb", "LBFGS"]


class Optimizer:
    def __init__(self, learning_rate=0.001, parameters=None, weight_decay=None,
                 grad_clip=None, name=None, multi_precision=True):
        if parameters is None:
            raise ValueError(
                "parameters must be given in dygraph mode "
                "(pass model.parameters())")
        self._parameter_list = list(parameters)
        self._param_groups = None
        if self._parameter_list and isinstance(self._parameter_list[0], dict):
            self._param_groups = self._parameter_list
            flat = []
            for g in self._param_groups:
                flat.extend(g["params"])
            self._parameter_list = flat
        self._learning_rate = learning_rate
        self._grad_clip = grad_clip
        self._weight_decay = weight_decay
        self._multi_precision = multi_precision
        self._accumulators: dict[str, dict[int, Tensor]] = {}
        self._master_weights: dict[int, Tensor] = {}
        # the step counter lives in a persistable device scalar (like
        # _lr_state below) so a to_static-compiled train step advances
        # it INSIDE the compiled program — a python int would only tick
        # on the discovery run and checkpoints saved after N compiled
        # steps would record step 1. The _step_count property keeps the
        # eager-facing int surface (state_dict "@step", tests).
        self._step_state = Tensor(jnp.asarray(0, jnp.int32))
        self._step_state.persistable = True
        self._step_state.name = "@step_state"
        # checkpoint loaded before the first step(): accumulators are lazy,
        # so stash the state and apply it as they get created
        self._pending_state: dict | None = None
        # lr lives in a persistable scalar so a to_static-compiled train
        # step reads the CURRENT lr as state input instead of baking the
        # trace-time value; scheduler.step() outside the compiled region
        # refreshes it (the jax-idiomatic "lr is part of opt state")
        self._lr_state = Tensor(jnp.asarray(self.get_lr(), jnp.float32))
        self._lr_state.persistable = True
        self._lr_state.name = "learning_rate"
        if isinstance(self._learning_rate, LRScheduler):
            self._learning_rate._bind(self)

    # -- lr ---------------------------------------------------------------

    def get_lr(self) -> float:
        if isinstance(self._learning_rate, LRScheduler):
            return float(self._learning_rate())
        return float(self._learning_rate)

    def set_lr(self, value: float) -> None:
        if isinstance(self._learning_rate, LRScheduler):
            raise RuntimeError(
                "cannot set_lr when a LRScheduler is in use")
        self._learning_rate = value
        self._sync_lr_state(value)

    def _sync_lr_state(self, value: float) -> None:
        from ..framework.core import trace_clean
        if trace_clean():
            self._lr_state.set_data(jnp.asarray(value, jnp.float32))

    # -- accumulators ------------------------------------------------------

    def _param_key(self, p: Tensor) -> str:
        if not hasattr(self, "_id2name"):
            self._id2name = {id(q): (q.name or f"param_{i}")
                             for i, q in enumerate(self._parameter_list)}
        return self._id2name.get(id(p), str(id(p)))

    def _acc(self, name: str, p: Tensor, init=None, dtype=None):
        store = self._accumulators.setdefault(name, {})
        key = id(p)
        if key not in store:
            data = jnp.zeros(p._data.shape, dtype or jnp.float32) \
                if init is None else init
            pending = (self._pending_state or {}).get(
                f"{self._param_key(p)}_{name}")
            if pending is not None:
                data = pending._data if isinstance(pending, Tensor) \
                    else jnp.asarray(pending)
            t = Tensor(data)
            t.persistable = True
            t.name = f"{self._param_key(p)}_{name}"
            store[key] = t
        return store[key]

    def _master(self, p: Tensor):
        """fp32 master weight for low-precision params."""
        if not self._multi_precision or p.dtype == jnp.float32 \
                or not is_floating(p.dtype):
            return None
        key = id(p)
        if key not in self._master_weights:
            data = p._data.astype(jnp.float32)
            pending = (self._pending_state or {}).get(
                f"{self._param_key(p)}_master")
            if pending is not None:
                data = pending._data if isinstance(pending, Tensor) \
                    else jnp.asarray(pending)
            t = Tensor(data)
            t.persistable = True
            self._master_weights[key] = t
        return self._master_weights[key]

    # -- step --------------------------------------------------------------

    def _collect_params_grads(self):
        from ..framework.segment import SegValue
        pgs = []
        for p in self._parameter_list:
            if not getattr(p, "trainable", True):
                continue
            g = p.grad
            if g is not None and isinstance(g._data, SegValue):
                # compile-around-break path: the backward tape was
                # recorded lazily; materialize every pending grad in ONE
                # flushed segment before the raw-jnp update math (which
                # cannot consume placeholders)
                g._data = g._data.force()
            pgs.append((p, g))
        return pgs

    def _decay_grad(self, p, gd):
        """Fold coupled weight decay into a raw grad array. Handles scalar
        coefficients and ``paddle.regularizer`` objects; a per-parameter
        regularizer attached via ParamAttr takes precedence over the
        optimizer-level ``weight_decay`` (paddle semantics)."""
        from ..regularizer import WeightDecayRegularizer
        wd = getattr(p, "regularizer", None)
        if wd is None:
            wd = self._weight_decay
        if wd is None or wd == 0.0:
            return gd
        pd = p._data.astype(gd.dtype)
        if isinstance(wd, WeightDecayRegularizer):
            return wd(pd, gd)
        coeff = float(wd) if not isinstance(wd, (list, tuple)) \
            else float(wd[0])
        return gd + coeff * pd

    def _apply_decay(self, p, g, lr):
        """L2 regularization folded into grad (paddle weight_decay on
        non-AdamW optimizers)."""
        return Tensor(self._decay_grad(p, g._data))

    def _lr_array(self):
        """Scalar lr used by update math. Outside a trace it is refreshed
        from the scheduler; inside a trace it is read as state, so compiled
        steps see per-call lr."""
        from ..framework.core import trace_clean
        if trace_clean():
            self._lr_state.set_data(jnp.asarray(self.get_lr(), jnp.float32))
        return self._lr_state.jax()

    @property
    def _step_count(self) -> int:
        st = self.__dict__.get("_step_state")
        if st is None:     # wrapper optimizers (LookAhead) that skip
            return self.__dict__.get("_step_count_py", 0)  # __init__
        return int(np.asarray(st._data))

    @_step_count.setter
    def _step_count(self, value) -> None:
        st = self.__dict__.get("_step_state")
        if st is None:
            self.__dict__["_step_count_py"] = int(value)
        else:
            st.set_data(jnp.asarray(int(value), jnp.int32))

    def step(self) -> None:
        with no_grad():
            pgs = [(p, g) for p, g in self._collect_params_grads()
                   if g is not None]
            if self._grad_clip is not None:
                pgs = self._grad_clip(pgs)
            lr = self._lr_array()
            for p, g in pgs:
                self._update_param(p, g, lr)
        # device-side increment, NOT the python property: inside a
        # compiled trace this must stay a traced op (int(tracer) would
        # be a per-step guard that mispredicts every call)
        self._step_state.set_data(self._step_state.jax() + 1)

    def _update_param(self, p: Tensor, g: Tensor, lr: float) -> None:
        raise NotImplementedError

    def minimize(self, loss, startup_program=None, parameters=None,
                 no_grad_set=None):
        loss.backward()
        self.step()
        self.clear_grad()
        return None, None

    def clear_grad(self, set_to_zero: bool = False) -> None:
        for p in self._parameter_list:
            p.clear_gradient(set_to_zero)

    clear_gradients = clear_grad

    # -- state dict --------------------------------------------------------

    def state_dict(self) -> dict:
        sd = {}
        for store in self._accumulators.values():
            for t in store.values():
                sd[t.name] = t
        for pid, t in self._master_weights.items():
            # master weights are keyed by param
            name = next((f"{self._param_key(p)}_master"
                         for p in self._parameter_list if id(p) == pid),
                        f"{pid}_master")
            sd[name] = t
        if isinstance(self._learning_rate, LRScheduler):
            sd["LR_Scheduler"] = self._learning_rate.state_dict()
        sd["@step"] = self._step_count
        return sd

    def set_state_dict(self, state: dict) -> None:
        """Restore optimizer state. Accumulators are created lazily at the
        first step, so state for not-yet-created slots is stashed and
        applied on creation (resume-before-first-step works). Values are
        snapshotted now — state_dict() hands out live tensors, and the
        source optimizer may keep stepping before our slots materialize."""
        self._pending_state = {
            k: (Tensor(v._data) if isinstance(v, Tensor) else v)
            for k, v in state.items()}
        for store in self._accumulators.values():
            for t in store.values():
                if t.name in state:
                    src = state[t.name]
                    t.set_data(src._data if isinstance(src, Tensor)
                               else jnp.asarray(src))
        for pid, t in self._master_weights.items():
            name = next((f"{self._param_key(p)}_master"
                         for p in self._parameter_list if id(p) == pid),
                        None)
            if name and name in state:
                src = state[name]
                t.set_data(src._data if isinstance(src, Tensor)
                           else jnp.asarray(src))
        if "LR_Scheduler" in state and isinstance(self._learning_rate,
                                                  LRScheduler):
            self._learning_rate.set_state_dict(state["LR_Scheduler"])
        self._step_count = state.get("@step", self._step_count)


class SGD(Optimizer):
    def __init__(self, learning_rate=0.001, parameters=None,
                 weight_decay=None, grad_clip=None, name=None, **kw):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         name)

    def _update_param(self, p, g, lr):
        gd = self._decay_grad(p, g._data)
        m = self._master(p)
        if m is not None:
            new = m._data - lr * gd.astype(jnp.float32)
            m.set_data(new)
            p.set_data(new.astype(p.dtype))
        else:
            p.set_data(p._data - (lr * gd).astype(p.dtype))


class Momentum(Optimizer):
    def __init__(self, learning_rate=0.001, momentum=0.9, parameters=None,
                 use_nesterov=False, weight_decay=None, grad_clip=None,
                 name=None, **kw):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         name)
        self._momentum = momentum
        self._nesterov = use_nesterov

    def _update_param(self, p, g, lr):
        gd = self._decay_grad(p, g._data.astype(jnp.float32))
        vel = self._acc("velocity", p)
        v = self._momentum * vel._data + gd
        vel.set_data(v)
        if self._nesterov:
            upd = gd + self._momentum * v
        else:
            upd = v
        m = self._master(p)
        if m is not None:
            new = m._data - lr * upd
            m.set_data(new)
            p.set_data(new.astype(p.dtype))
        else:
            p.set_data((p._data.astype(jnp.float32) - lr *
                        upd).astype(p.dtype))


class _AdamBase(Optimizer):
    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, parameters=None, weight_decay=None,
                 grad_clip=None, lazy_mode=False, multi_precision=True,
                 name=None, **kw):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         name, multi_precision)
        self._beta1 = beta1
        self._beta2 = beta2
        self._epsilon = epsilon

    def _adam_update(self, p, g, lr, decoupled_wd=0.0, apply_l2=True):
        gd = g._data.astype(jnp.float32)
        if apply_l2 and not decoupled_wd:
            gd = self._decay_grad(p, gd)
        m_t = self._acc("moment1", p)
        v_t = self._acc("moment2", p)
        b1p = self._acc("beta1_pow", p,
                        init=jnp.asarray(1.0, jnp.float32))
        b2p = self._acc("beta2_pow", p,
                        init=jnp.asarray(1.0, jnp.float32))
        b1 = self._beta1() if callable(self._beta1) else self._beta1
        b2 = self._beta2() if callable(self._beta2) else self._beta2
        m = b1 * m_t._data + (1 - b1) * gd
        v = b2 * v_t._data + (1 - b2) * jnp.square(gd)
        b1_pow = b1p._data * b1
        b2_pow = b2p._data * b2
        m_t.set_data(m)
        v_t.set_data(v)
        b1p.set_data(b1_pow)
        b2p.set_data(b2_pow)
        m_hat = m / (1 - b1_pow)
        v_hat = v / (1 - b2_pow)
        master = self._master(p)
        base = master._data if master is not None else \
            p._data.astype(jnp.float32)
        if decoupled_wd:
            base = base * (1.0 - lr * decoupled_wd)
        new = base - lr * m_hat / (jnp.sqrt(v_hat) + self._epsilon)
        if master is not None:
            master.set_data(new)
        p.set_data(new.astype(p.dtype))


class Adam(_AdamBase):
    def _update_param(self, p, g, lr):
        self._adam_update(p, g, lr)


class AdamW(_AdamBase):
    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, parameters=None, weight_decay=0.01,
                 lr_ratio=None, apply_decay_param_fun=None, grad_clip=None,
                 lazy_mode=False, multi_precision=True, name=None, **kw):
        super().__init__(learning_rate, beta1, beta2, epsilon, parameters,
                         None, grad_clip, lazy_mode, multi_precision, name)
        self._coeff = weight_decay
        self._apply_decay_param_fun = apply_decay_param_fun
        self._lr_ratio = lr_ratio

    def _update_param(self, p, g, lr):
        decay = self._coeff
        if self._apply_decay_param_fun is not None and \
                not self._apply_decay_param_fun(p.name):
            decay = 0.0
        if self._lr_ratio is not None:
            lr = lr * self._lr_ratio(p)
        self._adam_update(p, g, lr, decoupled_wd=decay, apply_l2=False)


class Adamax(_AdamBase):
    def _update_param(self, p, g, lr):
        gd = self._decay_grad(p, g._data.astype(jnp.float32))
        m_t = self._acc("moment", p)
        u_t = self._acc("inf_norm", p)
        b1p = self._acc("beta1_pow", p, init=jnp.asarray(1.0, jnp.float32))
        m = self._beta1 * m_t._data + (1 - self._beta1) * gd
        u = jnp.maximum(self._beta2 * u_t._data, jnp.abs(gd))
        b1_pow = b1p._data * self._beta1
        m_t.set_data(m)
        u_t.set_data(u)
        b1p.set_data(b1_pow)
        master = self._master(p)
        base = master._data if master is not None else \
            p._data.astype(jnp.float32)
        new = base - lr / (1 - b1_pow) * m / (u + self._epsilon)
        if master is not None:
            master.set_data(new)
        p.set_data(new.astype(p.dtype))


class Adagrad(Optimizer):
    def __init__(self, learning_rate, epsilon=1e-6, parameters=None,
                 weight_decay=None, grad_clip=None,
                 initial_accumulator_value=0.0, name=None, **kw):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         name)
        self._epsilon = epsilon
        self._init_acc = initial_accumulator_value

    def _update_param(self, p, g, lr):
        gd = self._decay_grad(p, g._data.astype(jnp.float32))
        acc = self._acc("moment", p,
                        init=jnp.full(p._data.shape, self._init_acc,
                                      jnp.float32))
        a = acc._data + jnp.square(gd)
        acc.set_data(a)
        p.set_data((p._data.astype(jnp.float32) -
                    lr * gd / (jnp.sqrt(a) + self._epsilon)).astype(p.dtype))


class Adadelta(Optimizer):
    def __init__(self, learning_rate=0.001, epsilon=1e-6, rho=0.95,
                 parameters=None, weight_decay=None, grad_clip=None,
                 name=None, **kw):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         name)
        self._epsilon = epsilon
        self._rho = rho

    def _update_param(self, p, g, lr):
        gd = self._decay_grad(p, g._data.astype(jnp.float32))
        avg_sq = self._acc("avg_squared_grad", p)
        avg_up = self._acc("avg_squared_update", p)
        asg = self._rho * avg_sq._data + (1 - self._rho) * jnp.square(gd)
        upd = jnp.sqrt(avg_up._data + self._epsilon) / \
            jnp.sqrt(asg + self._epsilon) * gd
        asu = self._rho * avg_up._data + (1 - self._rho) * jnp.square(upd)
        avg_sq.set_data(asg)
        avg_up.set_data(asu)
        p.set_data((p._data.astype(jnp.float32) - lr * upd).astype(p.dtype))


class RMSProp(Optimizer):
    def __init__(self, learning_rate, rho=0.95, epsilon=1e-6, momentum=0.0,
                 centered=False, parameters=None, weight_decay=None,
                 grad_clip=None, name=None, **kw):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         name)
        self._rho = rho
        self._epsilon = epsilon
        self._momentum = momentum
        self._centered = centered

    def _update_param(self, p, g, lr):
        gd = self._decay_grad(p, g._data.astype(jnp.float32))
        ms = self._acc("mean_square", p)
        mom = self._acc("momentum", p)
        new_ms = self._rho * ms._data + (1 - self._rho) * jnp.square(gd)
        ms.set_data(new_ms)
        if self._centered:
            mg = self._acc("mean_grad", p)
            new_mg = self._rho * mg._data + (1 - self._rho) * gd
            mg.set_data(new_mg)
            denom = jnp.sqrt(new_ms - jnp.square(new_mg) + self._epsilon)
        else:
            denom = jnp.sqrt(new_ms + self._epsilon)
        v = self._momentum * mom._data + lr * gd / denom
        mom.set_data(v)
        p.set_data((p._data.astype(jnp.float32) - v).astype(p.dtype))


class Lamb(_AdamBase):
    def __init__(self, learning_rate=0.001, lamb_weight_decay=0.01,
                 beta1=0.9, beta2=0.999, epsilon=1e-6, parameters=None,
                 grad_clip=None, exclude_from_weight_decay_fn=None,
                 name=None, **kw):
        super().__init__(learning_rate, beta1, beta2, epsilon, parameters,
                         None, grad_clip, name=name)
        self._lamb_wd = lamb_weight_decay
        self._exclude_fn = exclude_from_weight_decay_fn

    def _update_param(self, p, g, lr):
        gd = g._data.astype(jnp.float32)
        m_t = self._acc("moment1", p)
        v_t = self._acc("moment2", p)
        b1p = self._acc("beta1_pow", p, init=jnp.asarray(1.0, jnp.float32))
        b2p = self._acc("beta2_pow", p, init=jnp.asarray(1.0, jnp.float32))
        m = self._beta1 * m_t._data + (1 - self._beta1) * gd
        v = self._beta2 * v_t._data + (1 - self._beta2) * jnp.square(gd)
        b1_pow, b2_pow = b1p._data * self._beta1, b2p._data * self._beta2
        m_t.set_data(m); v_t.set_data(v)
        b1p.set_data(b1_pow); b2p.set_data(b2_pow)
        m_hat = m / (1 - b1_pow)
        v_hat = v / (1 - b2_pow)
        wd = self._lamb_wd
        if self._exclude_fn is not None and self._exclude_fn(p):
            wd = 0.0
        pf = p._data.astype(jnp.float32)
        r = m_hat / (jnp.sqrt(v_hat) + self._epsilon) + wd * pf
        w_norm = jnp.linalg.norm(pf)
        r_norm = jnp.linalg.norm(r)
        trust = jnp.where((w_norm > 0) & (r_norm > 0), w_norm / r_norm, 1.0)
        p.set_data((pf - lr * trust * r).astype(p.dtype))


class LBFGS(Optimizer):
    """Accepted for API parity; performs plain gradient descent with line
    search omitted (full L-BFGS is a later-phase item, rarely used in the
    baseline workloads)."""

    def __init__(self, learning_rate=1.0, max_iter=20, parameters=None,
                 **kw):
        super().__init__(learning_rate, parameters, None, None, None)

    def step(self, closure=None):
        loss = None
        if closure is not None:
            loss = closure()
        with no_grad():
            for p, g in self._collect_params_grads():
                if g is not None:
                    p.set_data(p._data - self.get_lr() * g._data)
        return loss


class Rprop(Optimizer):
    """Resilient backpropagation (paddle.optimizer.Rprop parity): per-
    element step sizes grown/shrunk by the sign agreement of successive
    gradients; only the gradient SIGN is used."""

    def __init__(self, learning_rate=0.001, learning_rate_range=(1e-5, 50),
                 parameters=None, etas=(0.5, 1.2), grad_clip=None,
                 multi_precision=False, name=None, **kw):
        super().__init__(learning_rate, parameters, None, grad_clip, name)
        self._lr_min, self._lr_max = (float(learning_rate_range[0]),
                                      float(learning_rate_range[1]))
        self._eta_minus, self._eta_plus = float(etas[0]), float(etas[1])

    def _update_param(self, p, g, lr):
        gd = g._data.astype(jnp.float32)
        prev = self._acc("prev_grad", p)
        step = self._acc("step_size", p,
                         init=jnp.full(p._data.shape, float(lr),
                                       jnp.float32))
        sign = jnp.sign(gd) * jnp.sign(prev._data)
        factor = jnp.where(sign > 0, self._eta_plus,
                           jnp.where(sign < 0, self._eta_minus, 1.0))
        new_step = jnp.clip(step._data * factor, self._lr_min, self._lr_max)
        # on sign flip: revert nothing (iRprop-), zero the stored grad so
        # the next step is neutral
        g_eff = jnp.where(sign < 0, 0.0, gd)
        upd = -jnp.sign(g_eff) * new_step
        prev.set_data(g_eff)
        step.set_data(new_step)
        p.set_data((p._data.astype(jnp.float32) + upd).astype(p.dtype))


class ASGD(Optimizer):
    """Averaged SGD (paddle.optimizer.ASGD parity): SGD steps plus a
    running average of the iterates stored per parameter."""

    def __init__(self, learning_rate=0.001, batch_num=1, parameters=None,
                 weight_decay=None, grad_clip=None, multi_precision=False,
                 name=None, **kw):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         name)
        self._batch_num = max(int(batch_num), 1)

    def _update_param(self, p, g, lr):
        gd = self._decay_grad(p, g._data.astype(jnp.float32))
        # running mean of the last batch_num grads (paddle keeps a
        # d-buffer; the streaming mean is the TPU-friendly equivalent)
        buf = self._acc("grad_mean", p)
        n_t = self._acc("n_seen", p, init=jnp.zeros((), jnp.float32))
        n = jnp.minimum(n_t._data + 1.0, float(self._batch_num))
        mean = buf._data + (gd - buf._data) / n
        buf.set_data(mean)
        n_t.set_data(n)
        p.set_data((p._data.astype(jnp.float32) - lr * mean)
                   .astype(p.dtype))


class _NAdamRAdamBase(_AdamBase):
    def _moments(self, p, gd):
        m_t = self._acc("moment1", p)
        v_t = self._acc("moment2", p)
        b1p = self._acc("beta1_pow", p, init=jnp.asarray(1.0, jnp.float32))
        b2p = self._acc("beta2_pow", p, init=jnp.asarray(1.0, jnp.float32))
        m = self._beta1 * m_t._data + (1 - self._beta1) * gd
        v = self._beta2 * v_t._data + (1 - self._beta2) * jnp.square(gd)
        b1 = b1p._data * self._beta1
        b2 = b2p._data * self._beta2
        m_t.set_data(m)
        v_t.set_data(v)
        b1p.set_data(b1)
        b2p.set_data(b2)
        return m, v, b1, b2

    def _write(self, p, new):
        master = self._master(p)
        if master is not None:
            master.set_data(new)
        p.set_data(new.astype(p.dtype))

    def _base(self, p):
        master = self._master(p)
        return master._data if master is not None else \
            p._data.astype(jnp.float32)


class NAdam(_NAdamRAdamBase):
    """Nesterov-momentum Adam (paddle.optimizer.NAdam parity)."""

    def _update_param(self, p, g, lr):
        gd = self._decay_grad(p, g._data.astype(jnp.float32))
        m, v, b1, b2 = self._moments(p, gd)
        m_hat = (self._beta1 * m / (1 - b1 * self._beta1)
                 + (1 - self._beta1) * gd / (1 - b1))
        v_hat = v / (1 - b2)
        new = self._base(p) - lr * m_hat / (jnp.sqrt(v_hat) + self._epsilon)
        self._write(p, new)


class RAdam(_NAdamRAdamBase):
    """Rectified Adam (paddle.optimizer.RAdam parity): per-step variance
    rectification; falls back to momentum SGD while the variance estimate
    is untrustworthy (small t)."""

    def _update_param(self, p, g, lr):
        gd = self._decay_grad(p, g._data.astype(jnp.float32))
        m, v, b1, b2 = self._moments(p, gd)
        rho_inf = 2.0 / (1 - self._beta2) - 1.0
        # t from beta2^t (avoids a separate step counter accumulator)
        t = jnp.log(b2) / jnp.log(jnp.asarray(self._beta2, jnp.float32))
        rho_t = rho_inf - 2.0 * t * b2 / (1 - b2)
        m_hat = m / (1 - b1)
        r_num = (rho_t - 4) * (rho_t - 2) * rho_inf
        r_den = (rho_inf - 4) * (rho_inf - 2) * rho_t
        rect = jnp.sqrt(jnp.maximum(r_num / jnp.maximum(r_den, 1e-30),
                                    0.0))
        v_hat = jnp.sqrt(v / (1 - b2))
        adam_step = rect * m_hat / (v_hat + self._epsilon)
        sgd_step = m_hat
        new = self._base(p) - lr * jnp.where(rho_t > 5.0, adam_step,
                                             sgd_step)
        self._write(p, new)
