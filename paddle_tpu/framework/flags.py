"""Runtime flag registry — the role of Paddle's gflags-workalike
(``paddle/phi/core/flags.h`` / ``PHI_DEFINE_EXPORTED_*``, UNVERIFIED).

Flags are defined in Python, ingested from ``FLAGS_*`` environment variables
at import, readable/mutable at runtime via ``get_flags``/``set_flags``
(mirroring ``paddle.get_flags``/``paddle.set_flags``).

Tuner interplay (docs/autotune.md): every flag records its value's
*source* — ``"default"`` (the define_flag literal), ``"env"`` (a
``FLAGS_*`` environment variable at import) or ``"set"`` (a runtime
``set_flags`` call). Knobs that are also tunable surfaces (e.g.
``FLAGS_flash_attn_block_q/kv``) resolve with the precedence

    explicit user value (env or set_flags)  >  tuner cache  >  default

so an operator pinning a block size always wins over a searched
config, and a searched config only ever replaces the built-in default
(:func:`flag_source` is how call sites distinguish the cases).
"""

from __future__ import annotations

import os
import threading
from typing import Any, Callable

__all__ = ["define_flag", "get_flags", "set_flags", "flag", "flag_source",
           "scoped_default"]

_lock = threading.Lock()
_registry: dict[str, dict] = {}


def _parse_env(value: str, typ):
    if typ is bool:
        return value.lower() in ("1", "true", "yes", "on")
    return typ(value)


def define_flag(name: str, default: Any, help: str = "",
                typ: type | None = None,
                on_change: Callable[[Any], None] | None = None) -> None:
    """Define ``FLAGS_<name>``. Reads initial value from env if present."""
    if not name.startswith("FLAGS_"):
        name = "FLAGS_" + name
    typ = typ if typ is not None else type(default)
    value = default
    source = "default"
    env = os.environ.get(name)
    if env is not None:
        try:
            value = _parse_env(env, typ)
            source = "env"
        except (TypeError, ValueError):
            pass
    with _lock:
        _registry[name] = {"value": value, "default": default, "help": help,
                           "type": typ, "on_change": on_change,
                           "source": source}


def flag(name: str) -> Any:
    if not name.startswith("FLAGS_"):
        name = "FLAGS_" + name
    return _registry[name]["value"]


def flag_source(name: str) -> str:
    """Where the flag's current value came from: ``"default"`` |
    ``"env"`` | ``"set"``. Anything but ``"default"`` is an explicit
    user choice, which beats tuner-cache values (module docstring)."""
    if not name.startswith("FLAGS_"):
        name = "FLAGS_" + name
    return _registry[name].get("source", "default")


def get_flags(flags: str | list[str] | None = None) -> dict[str, Any]:
    if flags is None:
        return {k: v["value"] for k, v in _registry.items()}
    if isinstance(flags, str):
        flags = [flags]
    out = {}
    for f in flags:
        key = f if f.startswith("FLAGS_") else "FLAGS_" + f
        out[key] = _registry[key]["value"]
    return out


def set_flags(flags: dict[str, Any]) -> None:
    for k, v in flags.items():
        key = k if k.startswith("FLAGS_") else "FLAGS_" + k
        with _lock:
            if key not in _registry:
                # Paddle tolerates unknown flags with a warning; we register.
                _registry[key] = {"value": v, "default": v, "help": "",
                                  "type": type(v), "on_change": None,
                                  "source": "set"}
                continue
            ent = _registry[key]
            ent["value"] = ent["type"](v) if not isinstance(v, ent["type"]) else v
            ent["source"] = "set"
            cb = ent["on_change"]
        if cb is not None:
            cb(v)


class scoped_default:
    """Context manager: give ``name`` a different DEFAULT for the scope.

    The new value applies only while the flag's current value came from
    the ``define_flag`` literal — an explicit env var or ``set_flags``
    call always wins (the module-docstring precedence), and the source
    stays ``"default"`` so tuner-cache resolution is unaffected. Value
    and source are restored on exit. This is how ``Model.fit`` turns
    ``FLAGS_fused_linear_cross_entropy`` on for the compiled hot path
    without overriding an operator's explicit choice."""

    def __init__(self, name: str, value: Any):
        self._name = name if name.startswith("FLAGS_") else \
            "FLAGS_" + name
        self._value = value
        self._applied = False

    def __enter__(self):
        cb = val = None
        with _lock:
            ent = _registry[self._name]
            self._prev = ent["value"]
            if ent["source"] == "default":
                ent["value"] = val = ent["type"](self._value)
                self._applied = True
                cb = ent["on_change"]
        # fire on_change outside the lock, same contract as set_flags —
        # callback-maintained state must track the scoped value too
        if self._applied and cb is not None:
            cb(val)
        return self

    def __exit__(self, *exc):
        cb = None
        restored = False
        with _lock:
            ent = _registry[self._name]
            # only roll back our own write: a set_flags inside the scope
            # is an explicit user choice and must survive
            if self._applied and ent["source"] == "default":
                ent["value"] = self._prev
                restored = True
                cb = ent["on_change"]
        if restored and cb is not None:
            cb(self._prev)
        return False


# -- core flags (mirroring commonly-used FLAGS_* names where sensible) ------
define_flag("FLAGS_check_nan_inf", False,
            "Check outputs for NaN/Inf after each op (debug).")
define_flag("FLAGS_cudnn_deterministic", False,
            "Determinism knob (XLA is deterministic by default; accepted for "
            "compatibility).")
define_flag("FLAGS_use_stride_kernel", False, "Accepted for compatibility.")
define_flag("FLAGS_embedding_deterministic", 0, "Accepted for compatibility.")
define_flag("FLAGS_allocator_strategy", "auto_growth",
            "Allocator strategy (PJRT owns allocation on TPU).")
define_flag("FLAGS_fraction_of_gpu_memory_to_use", 0.92,
            "Accepted for compatibility; PJRT flag controls TPU memory.")
define_flag("FLAGS_log_level", 1, "Framework log verbosity.")


def _toggle_host_trace(value):
    # lazy import: flags load before the profiler package exists. The
    # flag toggle never writes files; use profiler.disable() directly
    # for an export on stop.
    from ..profiler import disable, enable
    enable() if value else disable(export=False)


define_flag("FLAGS_enable_host_trace", False,
            "Structured host trace layer (paddle_tpu.profiler.trace): "
            "spans/gauges recorded process-wide, chrome-trace export on "
            "disable. Same switch as PADDLE_PROFILER_TRACE=1.",
            on_change=_toggle_host_trace)
define_flag("FLAGS_host_trace_level", 1,
            "Reserved verbosity knob for the host trace layer (parity "
            "with the reference profiler's FLAGS_host_trace_level; the "
            "structured tracer currently records all spans when "
            "enabled).")
define_flag("FLAGS_tpu_matmul_precision", "default",
            "Matmul precision: default|high|highest (maps to jax precision).")
define_flag("FLAGS_enable_pallas_kernels", True,
            "Use Pallas kernels (flash-attn, rms_norm, rope) when on TPU.")
# 256/512 measured best on v5e at hidden 2560 under remat (59.3% vs
# 57.4% MFU at 512/512 on the 4-layer tuning slice, 2026-07-31; the
# earlier 512/512 pick was tuned on the no-remat 0.89B config). Both
# kernels clamp to the padded sequence length. These are tunable
# surfaces ("flash_attention", paddle_tpu.tuner): an explicit env /
# set_flags value wins over a tuner-cache entry, which wins over the
# defaults here (flag_source distinguishes them).
define_flag("FLAGS_flash_attn_block_q", 256, "Pallas flash-attn q block.")
define_flag("FLAGS_flash_attn_block_kv", 512, "Pallas flash-attn kv block.")
define_flag("FLAGS_recompute_policy", "dots_saveable",
            "jax.checkpoint policy for recompute()/use_recompute: "
            "dots_saveable (default) | nothing_saveable | "
            "dots_with_no_batch_dims_saveable | everything_saveable.")
define_flag("FLAGS_flash_attn_pallas_bwd", True,
            "Flash-attn backward via the hand-written Pallas dkv/dq "
            "kernels (False = blockwise lax.scan recompute fallback).")
define_flag("FLAGS_use_pallas_paged_attention", 1,
            "ops.paged_attention.paged_attention (the standalone "
            "decode-step op + incubate API): use the jax Pallas "
            "decode kernel on TPU (0 = jnp gather/softmax reference). "
            "The serving engine's decode path no longer rides this op "
            "— it goes through the unified ragged entry point, gated "
            "by FLAGS_use_pallas_ragged_attention.")
define_flag("FLAGS_use_pallas_ragged_attention", 1,
            "Serving batching step: use the Pallas ragged "
            "paged-attention kernel (mixed prefill+decode, ONE "
            "program) on TPU (0 = jnp gather/softmax reference path).")
# These are a tunable surface ("ragged_paged_attention",
# paddle_tpu.tuner): an explicit env / set_flags value wins over a
# tuner-cache entry, which wins over the defaults here.
define_flag("FLAGS_ragged_attn_q_block", 16,
            "Ragged paged-attention: stream tokens per q program.")
define_flag("FLAGS_ragged_attn_kv_pages", 4,
            "Ragged paged-attention: KV pages per DMA compute block.")
define_flag("FLAGS_fused_linear_cross_entropy", False,
            "LM training loss: chunked fused lm_head-matmul +"
            " cross-entropy that never materializes [N, V] logits "
            "(ops/fused_ce.py); the labeled forward then returns "
            "(None, loss). Module default OFF for the bare labeled "
            "forward, but hapi.Model.fit(compiled=True) turns it on "
            "for the compiled hot path via flags.scoped_default (the "
            "memory headroom is what buys bigger per-chip batches "
            "there); an explicit env/set_flags value wins either way. "
            "fit(compiled=False) stays the eager UNFUSED parity "
            "oracle.")
define_flag("FLAGS_fused_ce_chunk_v", 1024,
            "Fused linear+CE vocab-chunk width. This is a tunable "
            "surface ('fused_ce', paddle_tpu.tuner): an explicit env/"
            "set_flags value wins over a tuner-cache entry, which wins "
            "over this default (flag_source distinguishes).")
define_flag("FLAGS_fused_ce_pallas_inner", True,
            "Fused linear+CE: run the per-chunk softmax stats and "
            "backward dlogits through the Pallas inner kernels "
            "(ops/pallas/ce_chunk.py) on TPU, keeping the scan body's "
            "elementwise work in VMEM (0 = pure jnp scan body).")
define_flag("FLAGS_fused_rmsnorm_residual", True,
            "Decoder hot path: fuse each residual-add with the "
            "following RMSNorm (ops/pallas/rms_norm.rms_norm_residual "
            "on TPU; identical-math jnp pairing elsewhere). The Llama "
            "unrolled stack carries a (hidden, residual) pair so BOTH "
            "norm+residual pairs per layer fuse; Qwen2/DeepSeek fuse "
            "the post-attention pair in place.")
define_flag("FLAGS_fused_swiglu", True,
            "MLP hot path: silu(gate)*up through the fused Pallas "
            "SwiGLU kernel on TPU (one VMEM pass fwd, fused dgate/dup "
            "bwd, no silu intermediate saved); jnp composition "
            "elsewhere.")
