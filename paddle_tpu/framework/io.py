"""``paddle.save`` / ``paddle.load`` — single-process checkpoint tier
(python/paddle/framework/io.py parity, UNVERIFIED; pickle ``.pdparams`` /
``.pdopt`` format in spirit). Tensors serialize as numpy arrays."""

from __future__ import annotations

import os
import pickle

import jax.numpy as jnp
import numpy as np

from .core import Tensor

__all__ = ["save", "load"]

_PROTOCOL = 4


class _TensorPlaceholder:
    def __init__(self, array: np.ndarray, stop_gradient: bool, name: str):
        self.array = array
        self.stop_gradient = stop_gradient
        self.name = name


def _pack(obj):
    if isinstance(obj, Tensor):
        return _TensorPlaceholder(np.asarray(obj._data), obj.stop_gradient,
                                  obj.name)
    if isinstance(obj, dict):
        return {k: _pack(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        t = type(obj)
        return t(_pack(v) for v in obj)
    return obj


def _unpack(obj, return_numpy=False):
    if isinstance(obj, _TensorPlaceholder):
        if return_numpy:
            return obj.array
        t = Tensor(jnp.asarray(obj.array), stop_gradient=obj.stop_gradient,
                   name=obj.name)
        return t
    if isinstance(obj, dict):
        return {k: _unpack(v, return_numpy) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        t = type(obj)
        return t(_unpack(v, return_numpy) for v in obj)
    return obj


def save(obj, path, protocol=_PROTOCOL, **configs) -> None:
    d = os.path.dirname(path)
    if d:
        os.makedirs(d, exist_ok=True)
    with open(path, "wb") as f:
        pickle.dump(_pack(obj), f, protocol=protocol)


def load(path, return_numpy=False, **configs):
    with open(path, "rb") as f:
        obj = pickle.load(f)
    return _unpack(obj, return_numpy=return_numpy)
