"""Lazy-segment executor — compile-around-graph-break (SURVEY.md §3.5).

The reference's SOT compiles the bytecode subgraphs on BOTH sides of a
genuine graph break. Our tracing design has no bytecode: a signature
whose discovery hits an unguardable concretization (``float(loss)``
branched on, ``.numpy()`` mid-function) used to drop the WHOLE function
to eager per-op dispatch. This module recovers the reference behavior
the jax way:

- Under ``segment_mode()``, ``core.apply`` does not execute ops. It
  records each dispatch as a node and returns ``SegValue`` placeholders
  (aval from ``jax.eval_shape`` — shape/dtype flow without compute).
- When Python NEEDS a value — a scalar concretization, ``.numpy()``,
  or any direct jax consumption (``__jax_array__``) — the recorder
  FLUSHES: every recorded node since the last flush is replayed inside
  ONE ``jax.jit`` call (XLA fuses the whole segment), results are bound
  back onto the placeholders, and Python continues eagerly past the
  break into the next segment.
- The function therefore runs as K = (#breaks + 1) compiled segments
  per call — a compiled prefix, the eager break, a compiled suffix —
  exactly the SOT split, with re-tracing per call but XLA compiles
  deduped by jax's HLO-keyed compilation cache.

Autograd composes: in segment mode ``apply`` records a node whose
GradNode re-runs ``jax.vjp`` of the op INSIDE a later segment (the
backward pass is itself recorded and flushed compiled) — a
rematerializing tape, numerically identical to the eager one.
"""

from __future__ import annotations

import contextlib
import threading

import jax
import jax.numpy as jnp
import numpy as np

__all__ = ["SegValue", "SegmentRecorder", "segment_mode",
           "current_recorder"]


class _SegTLS(threading.local):
    """Segment mode is a PER-THREAD property: a compiled-around-break
    call on the trainer thread must not capture unrelated ops running
    concurrently on other threads (the DevicePrefetcher/DataLoader
    collate threads dispatch jnp work mid-step — recording those as
    lazy placeholders corrupts their shapes)."""

    def __init__(self):
        self.recorder = None


_tls = _SegTLS()
_cache_checked: list = [False]


def current_recorder():
    return _tls.recorder


def _ensure_compile_cache():
    """Segmented flushes re-trace fresh closures every call; without the
    persistent (HLO-keyed) compilation cache, every flush of a LARGE
    segment would also pay a full XLA compile. Configure the cache once
    if — and only if — the app has not set one itself. Entries need
    >0.1s of compile time to persist, so the directory holds only
    programs worth caching even though the setting is process-global;
    genuinely tiny segments re-compile in milliseconds and stay out."""
    if _cache_checked[0]:
        return
    _cache_checked[0] = True
    if jax.config.jax_compilation_cache_dir:
        return
    import os
    import tempfile
    user = os.environ.get("USER") or os.environ.get("LOGNAME") or (
        str(os.getuid()) if hasattr(os, "getuid") else "anon")
    jax.config.update(
        "jax_compilation_cache_dir",
        os.path.join(tempfile.gettempdir(),
                     f"paddle_tpu_segment_xla_cache_{user}"))
    # jax's default persistence threshold is a full SECOND of compile
    # time — a segment compiling in 0.9s would re-pay that every call.
    # Persist anything over 0.1s; only genuinely tiny programs (which
    # re-compile in milliseconds) stay out of the cache.
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.1)


class SegValue:
    """Placeholder for one not-yet-computed op output.

    Carries shape/dtype (from abstract eval) so metadata flows without
    compute; materializes via the recorder on scalar reads, numpy
    export, or direct jax consumption."""

    __slots__ = ("aval", "node", "out_idx", "concrete", "recorder")

    def __init__(self, aval, node, out_idx, recorder):
        self.aval = aval
        self.node = node
        self.out_idx = out_idx
        self.concrete = None
        self.recorder = recorder

    # ---- metadata ---------------------------------------------------------
    @property
    def shape(self):
        return self.concrete.shape if self.concrete is not None \
            else self.aval.shape

    @property
    def dtype(self):
        return self.concrete.dtype if self.concrete is not None \
            else self.aval.dtype

    @property
    def ndim(self):
        return len(self.shape)

    @property
    def size(self):
        n = 1
        for s in self.shape:
            n *= int(s)
        return n

    # ---- materialization --------------------------------------------------
    def force(self):
        if self.concrete is None:
            self.recorder.flush()
        return self.concrete

    def __jax_array__(self):
        # any direct jnp/lax consumption outside apply(): materialize.
        # Correct (just unfused) — the safety net for stray jax calls.
        return self.force()

    def __array__(self, dtype=None):
        arr = np.asarray(self.force())
        return arr.astype(dtype) if dtype is not None else arr

    # ---- arithmetic used by the tape (grad accumulation etc.) -------------
    def _bin(self, other, fn, name):
        rec = self.recorder
        return rec.record(fn, [self, other], n_outputs=1, name=name)[0]

    def __add__(self, other):
        return self._bin(other, lambda a, b: a + b, "seg_add")

    __radd__ = __add__

    def __mul__(self, other):
        return self._bin(other, lambda a, b: a * b, "seg_mul")

    __rmul__ = __mul__

    def __sub__(self, other):
        return self._bin(other, lambda a, b: a - b, "seg_sub")

    def __truediv__(self, other):
        return self._bin(other, lambda a, b: a / b, "seg_div")

    def __neg__(self):
        rec = self.recorder
        return rec.record(lambda a: -a, [self], 1, "seg_neg")[0]

    def astype(self, dtype):
        rec = self.recorder
        return rec.record(lambda a: a.astype(dtype), [self], 1,
                          "seg_astype")[0]

    def reshape(self, *shape):
        rec = self.recorder
        if len(shape) == 1 and isinstance(shape[0], (tuple, list)):
            shape = tuple(shape[0])
        return rec.record(lambda a: a.reshape(shape), [self], 1,
                          "seg_reshape")[0]


class _Node:
    __slots__ = ("fn", "args", "kwargs", "n_outputs", "outs", "name")

    def __init__(self, fn, args, kwargs, n_outputs, name):
        self.fn = fn
        self.args = args
        self.kwargs = kwargs
        self.n_outputs = n_outputs
        self.outs = None
        self.name = name


class SegmentRecorder:
    """Records apply()-level op dispatches; flushes them as one jitted
    program when a value is needed."""

    def __init__(self):
        self.pending: list[_Node] = []
        self.flushes = 0        # segments executed (the "probe")
        self.ops_recorded = 0
        # (tensor, original value) undo log: segment-mode mutations must
        # be revertible if the call aborts before its final flush (the
        # eager retry must not see half-committed state). FIRST write
        # per tensor only — rollback needs the oldest value, and keeping
        # every intermediate would pin a previous copy of all state for
        # the whole call (double HBM on a large train step).
        self.mutations: list = []
        self._mutated: set = set()

    def log_mutation(self, tensor, old_data):
        key = ("data", id(tensor))
        if key in self._mutated:
            return
        self._mutated.add(key)
        self.mutations.append(("data", tensor, old_data))

    def log_grad_mutation(self, tensor, old_grad):
        key = ("grad", id(tensor))
        if key in self._mutated:
            return
        self._mutated.add(key)
        self.mutations.append(("grad", tensor, old_grad))

    def abort(self):
        """Discard everything pending and restore every tensor mutated
        during this recording (arrays AND grad bindings) to its
        pre-call state."""
        self.pending.clear()
        for kind, t, old in reversed(self.mutations):
            if kind == "data":
                t._data = old
            else:
                t._grad_value = old
        self.mutations.clear()
        self._mutated.clear()

    # ---- recording --------------------------------------------------------
    def record(self, fn, args, n_outputs, name=""):
        """args: list of SegValue | jax array | python scalar. Returns a
        tuple of SegValues (n_outputs)."""
        node = _Node(fn, list(args), {}, n_outputs, name)
        avals = self._eval_shape(node)
        outs = tuple(SegValue(a, node, i, self)
                     for i, a in enumerate(avals))
        node.outs = outs
        self.pending.append(node)
        self.ops_recorded += 1
        return outs

    def record_kw(self, fn, args, kwargs, n_outputs, name=""):
        node = _Node(fn, list(args), dict(kwargs), n_outputs, name)
        avals = self._eval_shape(node)
        outs = tuple(SegValue(a, node, i, self)
                     for i, a in enumerate(avals))
        node.outs = outs
        self.pending.append(node)
        self.ops_recorded += 1
        return outs

    def _eval_shape(self, node):
        def shaped(a):
            if isinstance(a, SegValue):
                return jax.ShapeDtypeStruct(a.shape, a.dtype)
            return a

        args = [shaped(a) for a in node.args]
        out = jax.eval_shape(lambda *a: node.fn(*a, **node.kwargs), *args)
        if node.n_outputs == 1:
            return [out]
        return list(out)

    # ---- flushing ---------------------------------------------------------
    def flush(self):
        """Execute every pending node inside one jit; bind results.

        Each flush wraps a FRESH closure in jax.jit (op closures are
        recreated per call, so executable reuse by structural key would
        risk wrong cache hits on closed-over constants): segmented calls
        re-TRACE per call, and the XLA compile — the expensive part —
        is deduped by the persistent HLO-keyed compilation cache, which
        ``_ensure_compile_cache`` turns on if the app has not."""
        if not self.pending:
            return
        _ensure_compile_cache()
        nodes, self.pending = self.pending, []
        # gather external (concrete) inputs in first-use order
        ext = []
        ext_ids = {}

        def ext_slot(a):
            key = id(a)
            if key not in ext_ids:
                ext_ids[key] = len(ext)
                ext.append(a)
            return ext_ids[key]

        plan = []   # per node: list of ('e', idx) | ('v', node_i, out_i)
        node_index = {id(n): i for i, n in enumerate(nodes)}
        for n in nodes:
            wiring = []
            for a in n.args:
                if isinstance(a, SegValue):
                    if a.concrete is not None:
                        wiring.append(("e", ext_slot(a.concrete)))
                    else:
                        owner = node_index.get(id(a.node))
                        if owner is None:
                            # produced by an even earlier flush
                            wiring.append(("e", ext_slot(a.force())))
                        else:
                            wiring.append(("v", owner, a.out_idx))
                    continue
                if isinstance(a, (jax.Array, np.ndarray)):
                    wiring.append(("e", ext_slot(a)))
                else:
                    wiring.append(("c", a))       # python scalar: bake
            plan.append(wiring)

        def seg_fn(*ext_arrays):
            results = []
            for n, wiring in zip(nodes, plan):
                args = []
                for w in wiring:
                    if w[0] == "e":
                        args.append(ext_arrays[w[1]])
                    elif w[0] == "v":
                        r = results[w[1]]
                        args.append(r[w[2]])
                    else:
                        args.append(w[1])
                out = n.fn(*args, **n.kwargs)
                results.append((out,) if n.n_outputs == 1 else tuple(out))
            flat = [o for r in results for o in r]
            return tuple(flat)

        flat = jax.jit(seg_fn)(*ext)
        i = 0
        for n in nodes:
            for o in n.outs:
                o.concrete = flat[i]
                i += 1
        self.flushes += 1


@contextlib.contextmanager
def segment_mode(recorder: SegmentRecorder):
    prev = _tls.recorder
    _tls.recorder = recorder
    try:
        yield recorder
    except BaseException:
        _tls.recorder = prev
        recorder.abort()   # roll back half-committed state mutations
        raise
    else:
        _tls.recorder = prev
        try:
            recorder.flush()
        except BaseException:
            # the exit flush itself failed (compile OOM, a recorded op
            # erroring under jit): same rollback guarantee applies
            recorder.abort()
            raise
