from .core import (Tensor, Parameter, apply, backward, no_grad, enable_grad,
                   is_grad_enabled, set_grad_enabled, to_jax_dtype,
                   dtype_name)
from . import device, flags, random
from .io import save, load

__all__ = ["Tensor", "Parameter", "apply", "backward", "no_grad",
           "enable_grad", "is_grad_enabled", "set_grad_enabled",
           "to_jax_dtype", "dtype_name", "device", "flags", "random",
           "save", "load"]
