"""Device / Place abstraction.

Plays the role of Paddle's ``Place`` hierarchy (``paddle/phi/common/place.h``,
UNVERIFIED — reference mount empty at survey time). On TPU the device runtime
(streams, contexts, allocators) is owned by PJRT/XLA, so this layer is a thin,
honest façade: Places name PJRT devices; there are no user-managed streams.
"""

from __future__ import annotations

import jax

__all__ = [
    "Place", "CPUPlace", "TPUPlace", "CUDAPlace", "XPUPlace", "CustomPlace",
    "set_device", "get_device", "device_count", "is_compiled_with_cuda",
    "is_compiled_with_xpu", "is_compiled_with_tpu", "place_of", "get_all_devices",
]


class Place:
    device_type = "unknown"

    def __init__(self, device_id: int = 0):
        self.device_id = int(device_id)

    def __repr__(self):
        return f"Place({self.device_type}:{self.device_id})"

    def __eq__(self, other):
        return (isinstance(other, Place)
                and self.device_type == other.device_type
                and self.device_id == other.device_id)

    def __hash__(self):
        return hash((self.device_type, self.device_id))

    def jax_device(self):
        devs = [d for d in jax.devices() if _kind(d) == self.device_type]
        if not devs:
            devs = jax.devices("cpu")
        return devs[min(self.device_id, len(devs) - 1)]


class CPUPlace(Place):
    device_type = "cpu"


class TPUPlace(Place):
    device_type = "tpu"


class CUDAPlace(Place):
    """Accepted for source compatibility; resolves to the accelerator
    (TPU if present, else CPU)."""
    device_type = "tpu"


class XPUPlace(Place):
    device_type = "tpu"


class CustomPlace(Place):
    def __init__(self, device_type: str, device_id: int = 0):
        super().__init__(device_id)
        self.device_type = device_type


def _kind(dev) -> str:
    p = dev.platform.lower()
    if p in ("tpu", "axon"):
        return "tpu"
    if p in ("gpu", "cuda", "rocm"):
        return "gpu"
    return "cpu"


_current_device: str | None = None


def get_all_devices():
    return jax.devices()


def device_count(device_type: str | None = None) -> int:
    if device_type is None:
        return len(jax.devices())
    return len([d for d in jax.devices() if _kind(d) == device_type])


def set_device(device: str) -> Place:
    """``paddle.set_device('tpu:0' | 'cpu' | 'gpu:0')``."""
    global _current_device
    name, _, idx = device.partition(":")
    idx = int(idx) if idx else 0
    name = {"gpu": "tpu", "cuda": "tpu", "xpu": "tpu"}.get(name, name)
    _current_device = f"{name}:{idx}"
    if name == "cpu":
        return CPUPlace(idx)
    return TPUPlace(idx)


def get_device() -> str:
    if _current_device is not None:
        return _current_device
    default = jax.devices()[0]
    return f"{_kind(default)}:{default.id}"


def default_place() -> Place:
    name, _, idx = get_device().partition(":")
    return CPUPlace(int(idx or 0)) if name == "cpu" else TPUPlace(int(idx or 0))


def place_of(data) -> Place:
    try:
        devs = list(data.devices())
        dev = devs[0]
        kind = _kind(dev)
        return CPUPlace(dev.id) if kind == "cpu" else TPUPlace(dev.id)
    except Exception:
        return default_place()


def is_compiled_with_cuda() -> bool:
    return False


def is_compiled_with_rocm() -> bool:
    return False


def is_compiled_with_xpu() -> bool:
    return False


def is_compiled_with_tpu() -> bool:
    return device_count("tpu") > 0
