"""Global default dtype (``paddle.get/set_default_dtype``)."""

from __future__ import annotations

import jax.numpy as jnp

from .core import to_jax_dtype

_default_dtype = jnp.float32


def set_default_dtype(d) -> None:
    global _default_dtype
    _default_dtype = to_jax_dtype(d)


def set_printoptions(precision=None, threshold=None, edgeitems=None,
                     sci_mode=None, linewidth=None):
    """paddle.set_printoptions — tensor repr formatting. Tensor __repr__
    renders through numpy, so this delegates to np.set_printoptions
    (sci_mode maps to numpy's ``suppress`` inverse)."""
    import numpy as np

    kw = {}
    if precision is not None:
        kw["precision"] = int(precision)
    if threshold is not None:
        kw["threshold"] = int(threshold)
    if edgeitems is not None:
        kw["edgeitems"] = int(edgeitems)
    if linewidth is not None:
        kw["linewidth"] = int(linewidth)
    if sci_mode is not None:
        kw["suppress"] = not bool(sci_mode)
    np.set_printoptions(**kw)


def get_default_dtype():
    return _default_dtype


def get_default_dtype_name() -> str:
    return jnp.dtype(_default_dtype).name
