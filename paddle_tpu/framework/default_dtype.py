"""Global default dtype (``paddle.get/set_default_dtype``)."""

from __future__ import annotations

import jax.numpy as jnp

from .core import to_jax_dtype

_default_dtype = jnp.float32


def set_default_dtype(d) -> None:
    global _default_dtype
    _default_dtype = to_jax_dtype(d)


def get_default_dtype():
    return _default_dtype


def get_default_dtype_name() -> str:
    return jnp.dtype(_default_dtype).name
