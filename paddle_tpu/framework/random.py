"""Global RNG state.

Paddle exposes a global seeded generator (``paddle.seed``) plus per-parallel-
axis generators (``get_rng_state_tracker`` in fleet, for TP-correct dropout).
jax wants explicit keys. Resolution: a named registry of ``Generator`` objects
each holding a persistable key tensor; every draw splits the key and writes
back, so the to_static functionalizer captures RNG state like any other state
(SURVEY.md §7 "hard parts": RNG under trace).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from .core import Tensor

__all__ = ["seed", "Generator", "default_generator", "get_rng_state",
           "set_rng_state", "next_key", "RNGStatesTracker",
           "get_rng_state_tracker"]


class Generator:
    def __init__(self, seed_: int = 0, name: str = "default"):
        self.name = name
        self._seed = seed_
        # key creation is LAZY: building a PRNGKey initializes the XLA
        # backend, and the module-level default generator would otherwise
        # do that at import time — breaking jax.distributed.initialize
        # (which must run before any backend init) for every worker that
        # imports paddle_tpu first
        self._state_t: Tensor | None = None

    @property
    def _state(self) -> Tensor:
        if self._state_t is None:
            t = Tensor(jax.random.PRNGKey(self._seed), stop_gradient=True)
            t.persistable = True
            t.name = f"rng_{self.name}"
            self._state_t = t
        return self._state_t

    def manual_seed(self, seed_: int) -> "Generator":
        self._seed = seed_
        if self._state_t is None:
            return self    # stays lazy: key built from _seed on first use
        self._state.set_data(jax.random.PRNGKey(seed_))
        return self

    def next_key(self):
        """Split: return a fresh subkey, store the new state."""
        key = self._state.jax()  # records a state read under tracking
        new_state, sub = jax.random.split(key)
        self._state.set_data(new_state)
        return sub

    def get_state(self) -> Tensor:
        return Tensor(self._state.jax())

    def set_state(self, state) -> None:
        data = state.jax() if isinstance(state, Tensor) else jnp.asarray(state)
        self._state.set_data(data)


default_generator = Generator(0, "default")


def seed(value: int) -> Generator:
    """``paddle.seed`` — reseed the default generator (and axis trackers)."""
    default_generator.manual_seed(value)
    _tracker.reseed_all(value)
    return default_generator


def next_key():
    return default_generator.next_key()


def get_rng_state():
    return [default_generator.get_state()]


def set_rng_state(states) -> None:
    if isinstance(states, (list, tuple)):
        states = states[0]
    default_generator.set_state(states)


class RNGStatesTracker:
    """Named RNG states for parallelism — mirrors fleet's
    ``get_rng_state_tracker`` (meta_parallel/parallel_layers/random.py,
    UNVERIFIED): e.g. dropout inside a TP region must differ per model-rank
    ('local_seed') but match across ('global_seed')."""

    def __init__(self):
        self.states: dict[str, Generator] = {}

    def add(self, name: str, seed_: int) -> None:
        if name in self.states:
            raise ValueError(f"RNG state {name!r} already exists")
        self.states[name] = Generator(seed_, name)

    def reseed_all(self, base_seed: int) -> None:
        for i, (name, gen) in enumerate(sorted(self.states.items())):
            gen.manual_seed(base_seed + 1000 + i)

    def rng_state(self, name: str = "global_seed"):
        import contextlib

        @contextlib.contextmanager
        def ctx():
            gen = self.states.get(name)
            if gen is None:
                # lazily create deterministically from the name; crc32 is
                # stable across processes (str hash is salted per process,
                # which would desync TP ranks)
                import zlib
                gen = Generator(zlib.crc32(name.encode()) % (2**31), name)
                self.states[name] = gen
            global default_generator
            from . import random as _self
            prev = _self.default_generator
            _self.default_generator = gen
            try:
                yield
            finally:
                _self.default_generator = prev
        return ctx()


_tracker = RNGStatesTracker()


def get_rng_state_tracker() -> RNGStatesTracker:
    return _tracker
