"""Core of the TPU-native framework: Tensor façade over ``jax.Array`` plus a
tape-based eager autograd engine.

Reference parity (see SURVEY.md §2.1/§3; reference mount was empty, paths
unverified): plays the role of Paddle's PHI core (``DenseTensor``,
``paddle/phi/core/``) + the eager autograd engine (``paddle/fluid/eager/``,
``GradNodeBase``/``RunBackward``).  Design is TPU-first instead of a port:

- A ``Tensor`` wraps an immutable ``jax.Array``; "in-place" ops rebind the
  wrapped array, preserving Python identity (Paddle semantics) while staying
  functional underneath (XLA semantics).
- Autograd does not need per-op grad kernels: every differentiable op is a
  pure jax function, and the tape records the ``jax.vjp`` residual closure.
  ``backward()`` walks the tape.  Under ``paddle_tpu.jit.to_static`` the same
  tape runs on tracers and lowers into one XLA program, so eager and compiled
  mode share one autograd implementation (Paddle needs two: eager GradNodes
  and static-graph grad ops).
- State (parameters, buffers, optimizer accumulators, RNG key) is observable
  via a read/write tracking hook so the trace-and-compile path can
  functionalize user code that mutates state imperatively.
"""

from __future__ import annotations

import contextlib
import threading
import warnings
from typing import Any, Callable, Sequence

import jax
import jax.numpy as jnp
import numpy as np

__all__ = [
    "Tensor",
    "Parameter",
    "apply",
    "backward",
    "no_grad",
    "enable_grad",
    "is_grad_enabled",
    "set_grad_enabled",
    "to_jax_dtype",
    "dtype_name",
    "track_state",
    "current_tracking",
]

# --------------------------------------------------------------------------
# dtype handling
# --------------------------------------------------------------------------

_DTYPE_ALIASES = {
    "float32": jnp.float32, "fp32": jnp.float32,
    "float64": jnp.float64, "fp64": jnp.float64, "double": jnp.float64,
    "float16": jnp.float16, "fp16": jnp.float16, "half": jnp.float16,
    "bfloat16": jnp.bfloat16, "bf16": jnp.bfloat16,
    "int8": jnp.int8, "uint8": jnp.uint8,
    "int16": jnp.int16, "int32": jnp.int32, "int64": jnp.int64,
    "bool": jnp.bool_,
    "complex64": jnp.complex64, "complex128": jnp.complex128,
    "float8_e4m3fn": jnp.float8_e4m3fn, "float8_e5m2": jnp.float8_e5m2,
}


def to_jax_dtype(dtype) -> jnp.dtype:
    """Normalize a user-facing dtype (string / numpy / jax) to a jnp dtype."""
    if dtype is None:
        return None
    if isinstance(dtype, str):
        try:
            return jnp.dtype(_DTYPE_ALIASES[dtype])
        except KeyError:
            raise ValueError(f"Unknown dtype name: {dtype!r}")
    if isinstance(dtype, Tensor):
        return dtype.dtype
    return jnp.dtype(dtype)


def dtype_name(dtype) -> str:
    return jnp.dtype(dtype).name


def trace_clean() -> bool:
    """True when no jax trace is in progress (i.e. eager host execution).
    Single wrapper around the unstable jax internal so a jax upgrade has
    one place to fix; falls back to 'clean' if the symbol moves."""
    try:
        from jax._src.core import trace_state_clean
    except ImportError:  # jax moved the symbol; assume eager
        return True
    return trace_state_clean()


def is_floating(dtype) -> bool:
    return jnp.issubdtype(jnp.dtype(dtype), jnp.floating)


def is_complex(dtype) -> bool:
    return jnp.issubdtype(jnp.dtype(dtype), jnp.complexfloating)


def is_integer(dtype) -> bool:
    return jnp.issubdtype(jnp.dtype(dtype), jnp.integer)


def _coerce_host_data(data, dtype):
    """Paddle creation-dtype semantics for host data: python floats (and
    lists of them) default to float32; python ints to int64; numpy arrays
    keep their own dtype (so an explicit np.float64 array stays float64)."""
    if dtype is not None or isinstance(data, np.ndarray):
        return data
    arr = np.asarray(data)
    if arr.dtype == np.float64:
        return arr.astype(np.float32)
    return arr


# --------------------------------------------------------------------------
# grad mode
# --------------------------------------------------------------------------

class _GradState(threading.local):
    def __init__(self):
        self.enabled = True


_grad_state = _GradState()


def is_grad_enabled() -> bool:
    return _grad_state.enabled


def set_grad_enabled(mode: bool) -> None:
    _grad_state.enabled = bool(mode)


class _NoGrad(contextlib.ContextDecorator):
    """``paddle.no_grad`` equivalent — usable as context manager or decorator."""

    def __enter__(self):
        self._prev = _grad_state.enabled
        _grad_state.enabled = False
        return self

    def __exit__(self, *exc):
        _grad_state.enabled = self._prev
        return False


class _EnableGrad(contextlib.ContextDecorator):
    def __enter__(self):
        self._prev = _grad_state.enabled
        _grad_state.enabled = True
        return self

    def __exit__(self, *exc):
        _grad_state.enabled = self._prev
        return False


no_grad = _NoGrad
enable_grad = _EnableGrad


# --------------------------------------------------------------------------
# state read/write tracking (used by jit.to_static functionalization)
# --------------------------------------------------------------------------

class StateTracking:
    """Records which persistable tensors are read / written during a call."""

    def __init__(self):
        self.read: dict[int, "Tensor"] = {}
        self.written: dict[int, "Tensor"] = {}

    def record_read(self, t: "Tensor") -> None:
        self.read.setdefault(id(t), t)

    def record_write(self, t: "Tensor") -> None:
        self.written.setdefault(id(t), t)


class _TrackState(threading.local):
    def __init__(self):
        self.current: StateTracking | None = None


_track_state = _TrackState()


def current_tracking() -> StateTracking | None:
    return _track_state.current


@contextlib.contextmanager
def track_state(tracking: StateTracking):
    prev = _track_state.current
    _track_state.current = tracking
    try:
        yield tracking
    finally:
        _track_state.current = prev


# --------------------------------------------------------------------------
# scalar concretization record/replay (to_static guarded specialization)
# --------------------------------------------------------------------------

class _ConcretizeState(threading.local):
    """SOT-style branch specialization support. During to_static discovery
    (eager) every scalar concretization (bool/int of a Tensor) is RECORDED;
    during the jit trace the same sites REPLAY the recorded value as a
    python constant and register the traced tensor as a GUARD output, so
    the compiled program can verify each step that the branch decisions
    still hold (mismatch -> re-specialize)."""

    def __init__(self):
        self.mode = None      # None | "record" | "replay"
        self.log = None       # list of (kind, value)
        self.cursor = 0
        self.guards = None    # replay: list of (traced_array, kind, value)


_concretize_state = _ConcretizeState()

#: set by utils.monitor.enable_op_stats(): called as hook(name, dtype)
#: from apply() — amp.debugging operator-stats collection
_op_stat_hook = None


@contextlib.contextmanager
def record_concretizations(log: list):
    st = _concretize_state
    prev = (st.mode, st.log, st.cursor, st.guards)
    st.mode, st.log, st.cursor, st.guards = "record", log, 0, None
    try:
        yield log
    finally:
        st.mode, st.log, st.cursor, st.guards = prev


@contextlib.contextmanager
def replay_concretizations(log: list, guards: list):
    st = _concretize_state
    prev = (st.mode, st.log, st.cursor, st.guards)
    st.mode, st.log, st.cursor, st.guards = "replay", log, 0, guards
    try:
        yield guards
    finally:
        st.mode, st.log, st.cursor, st.guards = prev


class GraphBreak(Exception):
    """Raised during a to_static replay trace when the graph cannot be
    captured (replay divergence or an unguardable concretization); the
    to_static runner treats it like jax's tracer errors: warn + eager
    fallback. A plain exception — jax's ConcretizationTypeError requires
    a Tracer to construct, and divergence can involve concrete data."""


def _replay_divergence(data, why: str):
    return GraphBreak(
        f"to_static replay diverged from the discovery run ({why}); "
        "breaking the graph")


class _ObsCell:
    """Bookkeeping for one observed float concretization site (a
    ``float()``/``.item()`` read recorded during to_static discovery)."""

    __slots__ = ("misused", "strict")

    def __init__(self, strict=False):
        self.misused = False
        self.strict = strict     # replay trace: misuse must abort, not flag


class ObservedFloat(float):
    """A float ``.item()``-read out of a to_static-captured function
    (SOT-style partial capture, SURVEY.md §3.5 "graph breaks").

    Observation-only uses — logging, formatting, returning the value —
    keep the graph compiled: the read becomes an extra program output
    (fresh every call when returned). Uses that would change the program
    — branching on it, feeding it back into tensor math, int() indexing —
    flag ``misused`` during discovery (→ eager fallback for the
    signature) and raise ``GraphBreak`` during a replay trace.
    Arithmetic propagates observation: the python result mirrors onto the
    traced scalar, so derived returned values stay fresh too.

    Only ``.item()`` reads get this treatment: CPython force-converts
    ``__float__`` results to exact float, so ``float(t)`` cannot carry
    the taint and stays a hard graph break (its warning steers users to
    ``.item()``). Known hole (documented divergence): conversions that
    coerce via ``__float__`` (``math.isnan(f)``, ``"%f" % f``) are
    treated as observation; branching on the coerced value goes
    undetected."""

    __slots__ = ("_origins", "_traced")

    def __new__(cls, value, origins=(), traced=None):
        obj = super().__new__(cls, value)
        obj._origins = tuple(origins)
        obj._traced = traced
        return obj

    def _misuse(self, what):
        strict = False
        for c in self._origins:
            c.misused = True
            strict = strict or c.strict
        if strict:
            raise GraphBreak(
                f"a float read from the compiled graph was used for "
                f"{what} — this cannot be captured (a stale value would "
                "change the program); breaking the graph")

    # -- uses that change the program: flag / abort ------------------------

    def __bool__(self):
        self._misuse("branching")
        return super().__bool__()

    def _cmp(self, name, other):
        self._misuse("a comparison (likely branching)")
        return getattr(float, name)(float(self), other)

    def __lt__(self, o):
        return self._cmp("__lt__", o)

    def __le__(self, o):
        return self._cmp("__le__", o)

    def __gt__(self, o):
        return self._cmp("__gt__", o)

    def __ge__(self, o):
        return self._cmp("__ge__", o)

    def __eq__(self, o):
        return self._cmp("__eq__", o)

    def __ne__(self, o):
        return self._cmp("__ne__", o)

    __hash__ = float.__hash__

    def __int__(self):
        self._misuse("int conversion (indexing/branching)")
        return super().__int__()

    __index__ = __trunc__ = __int__

    def __round__(self, *a):
        self._misuse("rounding to int")
        return float(self).__round__(*a)

    # -- observation-preserving arithmetic ---------------------------------

    def _binop(self, name, other):
        if not isinstance(other, (int, float)):
            return NotImplemented
        res = getattr(float, name)(float(self), float(other))
        if res is NotImplemented:
            return res
        origins = self._origins
        o_traced = None
        if isinstance(other, ObservedFloat):
            origins = origins + other._origins
            o_traced = other._traced
        traced = None
        if self._traced is not None or o_traced is not None:
            # keep the traced value's own dtype (no float32 forcing):
            # under x64 a float64 loss must mirror in float64, or
            # compiled-call results would drift from the eager discovery
            a = self._traced if self._traced is not None else float(self)
            b = o_traced if o_traced is not None else float(other)
            try:
                traced = getattr(jnp.asarray(a), name)(jnp.asarray(b))
                if traced is NotImplemented:
                    traced = None
            except Exception:
                traced = None
        return ObservedFloat(res, origins, traced)

    def __add__(self, o):
        return self._binop("__add__", o)

    __radd__ = __add__

    def __sub__(self, o):
        return self._binop("__sub__", o)

    def __rsub__(self, o):
        return self._binop("__rsub__", o)

    def __mul__(self, o):
        return self._binop("__mul__", o)

    __rmul__ = __mul__

    def __truediv__(self, o):
        return self._binop("__truediv__", o)

    def __rtruediv__(self, o):
        return self._binop("__rtruediv__", o)

    def __pow__(self, o):
        return self._binop("__pow__", o)

    def __rpow__(self, o):
        return self._binop("__rpow__", o)

    def __mod__(self, o):
        return self._binop("__mod__", o)

    def __rmod__(self, o):
        return self._binop("__rmod__", o)

    def __floordiv__(self, o):
        return self._binop("__floordiv__", o)

    def __rfloordiv__(self, o):
        return self._binop("__rfloordiv__", o)

    def __divmod__(self, o):
        return (self.__floordiv__(o), self.__mod__(o))

    def __rdivmod__(self, o):
        return (self.__rfloordiv__(o), self.__rmod__(o))

    def __neg__(self):
        return ObservedFloat(
            -float(self), self._origins,
            None if self._traced is None else -self._traced)

    def __pos__(self):
        return self

    def __abs__(self):
        return ObservedFloat(
            abs(float(self)), self._origins,
            None if self._traced is None else jnp.abs(self._traced))

    def __float__(self):
        # exact float (CPython deprecates returning a strict subclass
        # from __float__); the taint ends here — documented hole
        return float.__add__(self, 0.0)


def _is_obs_float_kind(kind, value):
    # only .item() reads: float() results are force-converted to exact
    # float by CPython, so they cannot carry the observation taint
    return (kind == "item" and isinstance(value, float)
            and not isinstance(value, bool))


def _concretize(data, kind: str, cast):
    """Single funnel for Tensor scalar conversions (bool/int/float/item)."""
    from .segment import SegValue as _SegValue
    if isinstance(data, _SegValue):
        # lazy-segment placeholder: a scalar read IS the graph break —
        # flush the recorded segment (one compiled program), then hand
        # Python the concrete value and keep going into the next segment
        data = data.force()
    st = _concretize_state
    if st.mode == "replay":
        if st.cursor >= len(st.log):
            raise _replay_divergence(data, "more concretizations than "
                                           "recorded")
        entry = st.log[st.cursor]
        rec_kind, rec_val = entry[0], entry[1]
        st.cursor += 1
        if rec_kind != kind:
            raise _replay_divergence(
                data, f"expected {rec_kind}, got {kind}")
        if isinstance(data, jax.core.Tracer):
            if not guardable_concretization(kind, rec_val):
                if _is_obs_float_kind(kind, rec_val):
                    # observed float read (SOT partial capture): hand the
                    # user code the recorded value but keep the TRACED
                    # scalar alongside — observation (logging, return)
                    # stays compiled; misuse aborts the trace (strict)
                    return ObservedFloat(rec_val, (_ObsCell(strict=True),),
                                         traced=data)
                raise GraphBreak(
                    f"a {kind} concretization cannot be value-guarded "
                    "(replaying a stale value would silently change "
                    "numerics); breaking the graph. Observation-only "
                    ".item() reads stay compiled — prefer .item() over "
                    "float() inside compiled functions")
            # guardable scalar: feed the recorded value, emit a guard
            st.guards.append((data, kind, rec_val))
            return rec_val
        val = cast(data)   # concrete even under trace: a baked constant
        if val != rec_val:
            raise _replay_divergence(
                data, f"constant changed {rec_val!r} -> {val!r}")
        return val
    val = cast(data)       # eager (record mode or plain): concrete value
    if st.mode == "record":
        if _is_obs_float_kind(kind, val) and not \
                guardable_concretization(kind, val):
            cell = _ObsCell()
            st.log.append((kind, val, cell))
            return ObservedFloat(val, (cell,))
        st.log.append((kind, val))
    return val


def guardable_concretization(kind: str, value) -> bool:
    """Branch decisions / index choices can be value-guarded. float
    concretizations can NOT — a replayed stale float would silently change
    numerics (logging, lr math), and an equality guard on a moving loss
    would mispredict every step — so they break the graph."""
    if kind in ("bool", "int"):
        return True
    return kind == "item" and isinstance(value, (bool, int, np.integer))


# --------------------------------------------------------------------------
# autograd tape
# --------------------------------------------------------------------------

class GradNode:
    """One tape entry.  Mirrors the role of Paddle's ``GradNodeBase``
    (paddle/fluid/eager/grad_node_info.h, UNVERIFIED) but holds a ``jax.vjp``
    residual closure instead of pointing at a hand-written grad kernel."""

    __slots__ = ("vjp_fn", "parents", "n_outputs", "out_grads", "name",
                 "pending", "out_avals", "_hooks")

    def __init__(self, vjp_fn, parents, n_outputs, name="", out_avals=None):
        self.vjp_fn = vjp_fn
        # parents: list of Tensors that required grad (inputs of the op)
        self.parents: list[Tensor] = parents
        self.n_outputs = n_outputs
        self.out_grads: list[Any] = [None] * n_outputs
        self.name = name
        self.pending = 0
        # (shape, dtype) per output so unseeded outputs can be zero-filled
        self.out_avals = out_avals
        self._hooks: list[Callable] | None = None

    def add_out_grad(self, idx: int, g):
        cur = self.out_grads[idx]
        self.out_grads[idx] = g if cur is None else cur + g


class Tensor:
    """Paddle-shaped tensor.  Wraps a ``jax.Array`` (or jax tracer).

    ``stop_gradient`` defaults to True, matching ``paddle.Tensor``; set to
    False (or use ``Parameter``) to take part in autograd.
    """

    # let Tensor win in e.g. np_array * tensor
    __array_priority__ = 100

    __slots__ = ("_data", "_stop_gradient", "_grad_value", "_grad_stale",
                 "_node", "_out_idx", "name", "persistable", "_grad_hooks",
                 "trainable", "__weakref__")

    def __init__(self, data, dtype=None, stop_gradient: bool = True,
                 name: str = ""):
        if isinstance(data, Tensor):
            data = data._data
        from .segment import SegValue as _SegValue
        if isinstance(data, _SegValue):
            # lazy-segment placeholder: keep lazy, but honor a requested
            # cast (recorded as a node — dropping it would silently
            # diverge from the eager path's dtype)
            if dtype is not None and data.dtype != to_jax_dtype(dtype):
                data = data.astype(to_jax_dtype(dtype))
        elif not isinstance(data, jax.Array) and \
                not isinstance(data, jax.core.Tracer):
            data = jnp.asarray(_coerce_host_data(data, dtype),
                               dtype=to_jax_dtype(dtype))
        elif dtype is not None and data.dtype != to_jax_dtype(dtype):
            data = data.astype(to_jax_dtype(dtype))
        self._data = data
        self._stop_gradient = stop_gradient
        self._grad_value: Tensor | None = None
        self._grad_stale = False
        self._node: GradNode | None = None
        self._out_idx: int = 0
        self.name = name
        self.persistable = False
        self.trainable = not stop_gradient
        self._grad_hooks: list[Callable] | None = None

    # -- data access -------------------------------------------------------

    @property
    def data(self) -> "Tensor":
        return self

    @data.setter
    def data(self, value):
        self.set_data(value._data if isinstance(value, Tensor) else jnp.asarray(value))

    def jax(self):
        """The underlying jax.Array (TPU-native escape hatch)."""
        tr = _track_state.current
        if tr is not None and self.persistable:
            tr.record_read(self)
        return self._data

    def set_data(self, new_data, *, _clear_tape: bool = True) -> None:
        """Rebind the wrapped array. This is the single mutation point, so the
        to_static functionalizer can observe writes."""
        tr = _track_state.current
        if tr is not None and self.persistable:
            tr.record_write(self)
        from .segment import current_recorder
        rec = current_recorder()
        if rec is not None:
            # segment mode: log for rollback — a call that aborts before
            # its final flush must not leave half-committed state
            rec.log_mutation(self, self._data)
        self._data = new_data
        if _clear_tape:
            self._node = None
            self._out_idx = 0

    @property
    def stop_gradient(self) -> bool:
        return self._stop_gradient

    @stop_gradient.setter
    def stop_gradient(self, value: bool) -> None:
        self._stop_gradient = bool(value)

    @property
    def grad(self) -> "Tensor | None":
        if self._grad_stale:
            warnings.warn(
                "reading .grad after a compiled to_static step: gradients "
                "are consumed inside the compiled program and are NOT "
                "synchronized back to eager .grad — this value is stale or "
                "None. Inspect grads inside the compiled function, or run "
                "the step eagerly.", UserWarning, stacklevel=2)
            self._grad_stale = False
        return self._grad_value

    @grad.setter
    def grad(self, value) -> None:
        from .segment import current_recorder
        rec = current_recorder()
        if rec is not None:
            # abort-rollback must undo grad (re)binding too, or the
            # eager retry's backward would double-accumulate
            rec.log_grad_mutation(self, self._grad_value)
        self._grad_value = value
        self._grad_stale = False

    # -- metadata ----------------------------------------------------------

    @property
    def shape(self):
        return list(self._data.shape)

    @property
    def dtype(self):
        return self._data.dtype

    @property
    def ndim(self):
        return self._data.ndim

    @property
    def size(self):
        return int(np.prod(self._data.shape)) if self._data.ndim else 1

    @property
    def place(self):
        from . import device
        return device.place_of(self._data)

    @property
    def is_leaf(self) -> bool:
        return self._node is None

    def numel(self):
        from ..ops import creation
        return creation.to_tensor(self.size, dtype="int64")

    def dim(self):
        return self.ndim

    def rank(self):
        return self.ndim

    def element_size(self) -> int:
        return self._data.dtype.itemsize

    # -- conversion --------------------------------------------------------

    def numpy(self) -> np.ndarray:
        return np.asarray(self._data)

    def __array__(self, dtype=None):
        a = np.asarray(self._data)
        return a.astype(dtype) if dtype is not None else a

    def item(self):
        return _concretize(self._data, "item", lambda d: d.item())

    def tolist(self):
        return np.asarray(self._data).tolist()

    def __float__(self):
        return _concretize(self._data, "float", float)

    def __int__(self):
        return _concretize(self._data, "int", int)

    def __index__(self):
        return _concretize(self._data, "int", int)

    def __bool__(self):
        return _concretize(self._data, "bool", bool)

    def __len__(self):
        if self.ndim == 0:
            raise TypeError("len() of a 0-d tensor")
        return self._data.shape[0]

    def __repr__(self):
        try:
            body = repr(np.asarray(self._data))
        except Exception:  # tracers
            body = repr(self._data)
        return (f"Tensor(shape={self.shape}, dtype={dtype_name(self.dtype)}, "
                f"stop_gradient={self._stop_gradient},\n       {body})")

    def __hash__(self):
        return id(self)

    def __iter__(self):
        for i in range(len(self)):
            yield self[i]

    # -- autograd ----------------------------------------------------------

    def detach(self) -> "Tensor":
        t = Tensor(self._data, stop_gradient=True)
        return t

    def detach_(self) -> "Tensor":
        self._node = None
        self._stop_gradient = True
        return self

    def clone(self) -> "Tensor":
        from ..ops import manipulation
        return manipulation.clone(self)

    def backward(self, grad_tensor=None, retain_graph: bool = False) -> None:
        backward([self], [grad_tensor] if grad_tensor is not None else None,
                 retain_graph=retain_graph)

    def clear_grad(self) -> None:
        self.grad = None

    def clear_gradient(self, set_to_zero: bool = False) -> None:
        self._grad_stale = False   # explicit reset supersedes staleness
        if set_to_zero and self._grad_value is not None:
            self._grad_value.set_data(
                jnp.zeros_like(self._grad_value._data))
        else:
            self.grad = None

    def register_hook(self, hook: Callable) -> Callable:
        """Register a grad hook fired when this tensor's grad is computed.
        Returns a remover callable."""
        if self._grad_hooks is None:
            self._grad_hooks = []
        self._grad_hooks.append(hook)

        def remove():
            try:
                self._grad_hooks.remove(hook)
            except ValueError:
                pass
        return remove

    @property
    def requires_grad(self) -> bool:  # torch-style alias used in tests
        return not self._stop_gradient

    # in-place helpers used by optimizers (no autograd)
    def _inplace_update(self, new_data):
        self.set_data(new_data)
        return self


class Parameter(Tensor):
    """Trainable, persistable tensor — ``paddle.nn.Parameter`` equivalent."""

    def __init__(self, data, dtype=None, name: str = "", trainable: bool = True):
        super().__init__(data, dtype=dtype, stop_gradient=not trainable,
                         name=name)
        self.persistable = True
        self.trainable = trainable


# --------------------------------------------------------------------------
# op dispatch: eager execution + tape recording
# --------------------------------------------------------------------------

def _wrap_out(data, node=None, idx=0, stop_gradient=True):
    t = Tensor(data, stop_gradient=stop_gradient)
    if node is not None:
        t._node = node
        t._out_idx = idx
    return t


def apply(fn: Callable, *tensors, n_outputs: int = 1, name: str = "",
          differentiable: bool = True, **static_kwargs):
    """Execute op ``fn(*arrays, **static_kwargs)`` over Tensor inputs.

    The single entry point every op goes through (the analogue of Paddle's
    generated ``*_ad_func`` + PHI API dispatch, SURVEY.md §3.1). Handles:
      - unwrapping Tensors (and passing through python scalars),
      - state-read tracking for the to_static functionalizer,
      - recording a GradNode via ``jax.vjp`` when grad is required.

    ``fn`` must be a pure jax function. Tensor-valued kwargs are not allowed;
    pass tensors positionally.
    """
    if _op_stat_hook is not None:
        _op_stat_hook(name, str(getattr(
            next((t._data for t in tensors if isinstance(t, Tensor)),
                 None), "dtype", "-")))
    from . import segment as _segment
    rec = _segment.current_recorder()
    tr = _track_state.current
    datas = []
    for t in tensors:
        if isinstance(t, Tensor):
            if tr is not None and t.persistable:
                tr.record_read(t)
            if rec is None and \
                    isinstance(t._data, _segment.SegValue) and \
                    t._data.concrete is not None:
                # normalize a flushed placeholder back to its array the
                # first time it is touched outside segment mode
                t._data = t._data.concrete
            datas.append(t._data)
        else:
            if isinstance(t, ObservedFloat):
                # a float read out of the compiled graph feeding back into
                # tensor math: the recorded value would go stale — flag
                # (discovery) or abort the trace (replay)
                t._misuse("tensor computation")
            datas.append(t)

    needs_grad = (
        differentiable
        and _grad_state.enabled
        and any(isinstance(t, Tensor) and not t._stop_gradient for t in tensors)
    )

    if rec is not None:
        return _apply_segment(rec, fn, tensors, datas, n_outputs, name,
                              static_kwargs, needs_grad)

    if not needs_grad:
        out = fn(*datas, **static_kwargs)
        if n_outputs == 1:
            return _wrap_out(out)
        return tuple(_wrap_out(o) for o in out)

    # Differentiate only w.r.t. inputs that require grad; close over the rest.
    diff_idx = [i for i, t in enumerate(tensors)
                if isinstance(t, Tensor) and not t._stop_gradient]
    diff_parents = [tensors[i] for i in diff_idx]

    def pure(*diff_args):
        full = list(datas)
        for i, a in zip(diff_idx, diff_args):
            full[i] = a
        return fn(*full, **static_kwargs)

    out, vjp_fn = jax.vjp(pure, *(datas[i] for i in diff_idx))
    if n_outputs == 1:
        node = GradNode(vjp_fn, diff_parents, 1, name=name or fn.__name__,
                        out_avals=[(out.shape, out.dtype)])
        return _wrap_out(out, node, 0, stop_gradient=False)
    node = GradNode(vjp_fn, diff_parents, n_outputs, name=name or fn.__name__,
                    out_avals=[(o.shape, o.dtype) for o in out])
    outs = tuple(
        _wrap_out(o, node, i, stop_gradient=False) for i, o in enumerate(out)
    )
    return outs


def _apply_segment(rec, fn, tensors, datas, n_outputs, name,
                   static_kwargs, needs_grad):
    """apply() under segment mode: record the op instead of running it
    (compile-around-break — see framework/segment.py). The GradNode's
    vjp re-runs ``jax.vjp`` of the op inside a LATER segment, so the
    backward pass is recorded-and-flushed compiled too (a
    rematerializing tape with identical numerics)."""
    from . import segment as _segment
    opname = name or getattr(fn, "__name__", "op")
    outs = rec.record_kw(fn, datas, static_kwargs, n_outputs, opname)
    if not needs_grad:
        if n_outputs == 1:
            return _wrap_out(outs[0])
        return tuple(_wrap_out(o) for o in outs)

    diff_idx = [i for i, t in enumerate(tensors)
                if isinstance(t, Tensor) and not t._stop_gradient]
    diff_parents = [tensors[i] for i in diff_idx]

    def lazy_vjp(cts):
        ct_list = [cts] if n_outputs == 1 else list(cts)
        n_ct = len(ct_list)

        def grad_fn(*args):
            cta = args[:n_ct]
            full = list(args[n_ct:])

            def pure(*diff_args):
                f2 = list(full)
                for i, a in zip(diff_idx, diff_args):
                    f2[i] = a
                return fn(*f2, **static_kwargs)

            _, vjp = jax.vjp(pure, *(full[i] for i in diff_idx))
            gr = tuple(vjp(cta[0] if n_outputs == 1 else tuple(cta)))
            # the recorder's single-output contract is an unwrapped
            # value, not a 1-tuple
            return gr[0] if len(diff_idx) == 1 else gr

        rec2 = _segment.current_recorder()
        if rec2 is not None:
            return rec2.record(grad_fn, ct_list + list(datas),
                               n_outputs=len(diff_idx),
                               name=opname + "_bwd")
        # backward pulled outside segment mode: run on concrete values
        conc = [a.force() if isinstance(a, _segment.SegValue) else a
                for a in ct_list + list(datas)]
        gr = grad_fn(*conc)
        return (gr,) if len(diff_idx) == 1 else gr

    node = GradNode(lazy_vjp, diff_parents, n_outputs, name=opname,
                    out_avals=[(o.shape, o.dtype) for o in outs])
    if n_outputs == 1:
        return _wrap_out(outs[0], node, 0, stop_gradient=False)
    return tuple(_wrap_out(o, node, i, stop_gradient=False)
                 for i, o in enumerate(outs))


# --------------------------------------------------------------------------
# backward engine
# --------------------------------------------------------------------------

def _ones_like(data):
    from .segment import SegValue as _SegValue
    if isinstance(data, _SegValue):
        rec = data.recorder
        return rec.record(jnp.ones_like, [data], 1, "ones_like")[0]
    return jnp.ones_like(data)


def backward(tensors: Sequence[Tensor], grad_tensors=None,
             retain_graph: bool = False, accumulate_ids=None) -> None:
    """Run reverse-mode over the recorded tape — the analogue of
    ``egr::Backward`` (paddle/fluid/eager/backward.cc, UNVERIFIED).

    Topologically orders reachable GradNodes by dependency counting, then
    pulls vjp closures in reverse order, accumulating into ``.grad`` of leaf
    tensors with ``stop_gradient=False``. ``accumulate_ids`` (used by
    ``paddle.grad``) additionally accumulates into the named *non-leaf*
    tensors as their cotangents stream past."""
    roots = [t for t in tensors if isinstance(t, Tensor)]
    accumulate_ids = accumulate_ids or frozenset()
    if grad_tensors is None:
        grad_tensors = [None] * len(roots)
    # 1) seed grads
    for t, g in zip(roots, grad_tensors):
        if t._stop_gradient:
            continue
        seed = g._data if isinstance(g, Tensor) else (
            jnp.asarray(g, dtype=t.dtype) if g is not None else _ones_like(t._data))
        if id(t) in accumulate_ids:
            _accumulate_leaf(t, seed)
        if t._node is None:
            if id(t) not in accumulate_ids:
                _accumulate_leaf(t, seed)
        else:
            t._node.add_out_grad(t._out_idx, seed)

    # 2) collect reachable node graph & in-degrees (number of child nodes
    #    that will feed grads into each node)
    nodes: dict[int, GradNode] = {}
    indeg: dict[int, int] = {}
    stack = [t._node for t in roots if t._node is not None and not t._stop_gradient]
    seen = set()
    while stack:
        node = stack.pop()
        if id(node) in seen:
            continue
        seen.add(id(node))
        nodes[id(node)] = node
        for p in node.parents:
            pn = p._node
            if pn is not None:
                indeg[id(pn)] = indeg.get(id(pn), 0) + 1
                if id(pn) not in seen:
                    stack.append(pn)

    # 3) ready queue: nodes all of whose consumers have fired
    ready = [n for nid, n in nodes.items() if indeg.get(nid, 0) == 0]
    fired = set()
    while ready:
        node = ready.pop()
        fired.add(id(node))
        grads_out = tuple(
            g if g is not None else jnp.zeros(av[0], av[1])
            for g, av in zip(node.out_grads, node.out_avals)
        )
        in_grads = node.vjp_fn(grads_out[0] if node.n_outputs == 1 else grads_out)
        if not retain_graph:
            node.vjp_fn = None
        for parent, g in zip(node.parents, in_grads):
            pn = parent._node
            if g is None:
                # still release the dependency edge so upstream nodes fire
                if pn is not None:
                    indeg[id(pn)] -= 1
                    if indeg[id(pn)] == 0:
                        ready.append(pn)
                continue
            if parent._grad_hooks:
                gt = Tensor(g, stop_gradient=True)
                for hook in parent._grad_hooks:
                    res = hook(gt)
                    if res is not None:
                        gt = res if isinstance(res, Tensor) else Tensor(res)
                g = gt._data
            if id(parent) in accumulate_ids:
                _accumulate_leaf(parent, g)
            if pn is None:
                if not parent._stop_gradient and \
                        id(parent) not in accumulate_ids:
                    _accumulate_leaf(parent, g)
            else:
                pn.add_out_grad(parent._out_idx, g)
                indeg[id(pn)] -= 1
                if indeg[id(pn)] == 0:
                    ready.append(pn)
        node.out_grads = [None] * node.n_outputs
    # Nodes never fired (unreached due to missing seeds) are fine — their
    # vjp closures get collected with the tape.


def tape_alias(t: Tensor) -> Tensor:
    """A fresh Tensor sharing t's data AND tape position. In-place ops must
    run the functional op on an alias — recording the op with the mutated
    tensor itself as parent would create a self-referential node."""
    a = Tensor(t._data, stop_gradient=t._stop_gradient)
    a._node, a._out_idx = t._node, t._out_idx
    return a


def tape_rebind(t: Tensor, out: Tensor) -> Tensor:
    """Point t at out's data and tape node (the in-place op epilogue)."""
    t.set_data(out._data, _clear_tape=False)
    t._node, t._out_idx = out._node, out._out_idx
    t._stop_gradient = out._stop_gradient
    return t


def _accumulate_leaf(t: Tensor, g) -> None:
    if g.dtype != t.dtype and is_floating(t.dtype):
        g = g.astype(t.dtype)
    # _grad_value, not .grad: accumulating fresh grads must not trip the
    # stale-after-compiled-step warning (and it supersedes staleness)
    if t._grad_value is None:
        t.grad = Tensor(g, stop_gradient=True)
    else:
        t._grad_value.set_data(t._grad_value._data + g)
        t._grad_stale = False
