"""Varying-manual-axes (vma) helper shared by the manual-collective
engines (pipeline scan carries, ring-attention scan carries).

Inside a shard_map region, jax tracks which named axes a value is
device-varying over; freshly created constants (zeros carries) start
invariant and must be explicitly marked before a ``lax.scan`` whose
outputs vary — otherwise the carry types mismatch. This helper is the
one place that knows the pcast/pvary API difference and how to read a
value's current vma."""

from __future__ import annotations

import jax
from jax import lax

__all__ = ["pvary_missing"]


def pvary_missing(x, axes=(), like=None):
    """Mark ``x`` device-varying over ``axes`` plus every axis ``like``
    already varies on, skipping axes ``x`` is already varying over."""
    want = set(axes)
    if like is not None:
        try:
            want |= set(jax.typeof(like).vma)
        except Exception:
            pass
    try:
        want -= set(jax.typeof(x).vma)
    except Exception:
        pass
    if not want:
        return x
    if hasattr(lax, "pcast"):
        return lax.pcast(x, tuple(want), to="varying")
    try:
        return lax.pvary(x, tuple(want))
    except (AttributeError, TypeError):
        return x
