"""paddle.linalg — linear-algebra namespace.

Reference surface: upstream ``python/paddle/linalg.py`` (UNVERIFIED — the
reference mount was empty; see SURVEY.md provenance warning), which
re-exports from ``python/paddle/tensor/linalg.py``. Implementations live in
``paddle_tpu/ops/linalg.py`` (jax.numpy.linalg / lax.linalg — XLA lowers
these to MXU-friendly routines); this module adds the APIs upstream exposes
only under ``paddle.linalg``.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from .framework.core import Tensor, apply
from .ops.common import as_tensor
from .ops.linalg import (  # noqa: F401
    cholesky, cholesky_inverse, cholesky_solve, cond, corrcoef, cov, det,
    eig, eigh, eigvals, eigvalsh, householder_product, inv, lstsq, lu,
    matmul, matrix_power, matrix_rank, multi_dot, norm, pdist, pinv, qr,
    slogdet, solve, svd, triangular_solve,
)


def vector_norm(x, p=2.0, axis=None, keepdim=False, name=None):
    """L-p vector norm (flattens when axis is None)."""
    def fn(a):
        ax = axis
        if ax is None:
            a = a.reshape(-1)
            ax = 0
        return jnp.linalg.norm(a, ord=p, axis=ax, keepdims=keepdim)
    return apply(fn, as_tensor(x), name="vector_norm")


def matrix_norm(x, p="fro", axis=(-2, -1), keepdim=False, name=None):
    def fn(a):
        return jnp.linalg.norm(a, ord=p, axis=tuple(axis), keepdims=keepdim)
    return apply(fn, as_tensor(x), name="matrix_norm")


def matrix_transpose(x, name=None):
    return apply(lambda a: jnp.swapaxes(a, -1, -2), as_tensor(x),
                 name="matrix_transpose")


def matrix_exp(x, name=None):
    """Matrix exponential (scaling-and-squaring Padé via jax.scipy)."""
    from jax.scipy.linalg import expm
    return apply(expm, as_tensor(x), name="matrix_exp")


def lu_unpack(x, y, unpack_ludata=True, unpack_pivots=True, name=None):
    """Unpack the packed LU factorization produced by ``paddle.linalg.lu``.

    ``x``: packed LU matrix; ``y``: 1-based pivot vector. Returns (P, L, U).
    """
    a = as_tensor(x)
    m, n = int(a.shape[-2]), int(a.shape[-1])
    k = min(m, n)
    P = L = U = None
    if unpack_ludata:
        L = apply(lambda t: jnp.tril(t[..., :, :k], -1)
                  + jnp.eye(m, k, dtype=t.dtype), a, name="lu_unpack_L")
        U = apply(lambda t: jnp.triu(t[..., :k, :]), a, name="lu_unpack_U")
    if unpack_pivots:
        piv = as_tensor(y)
        pdtype = a.jax().dtype

        def perm_mat(pv):
            def one(p1):
                perm = jnp.arange(m)

                def body(i, perm):
                    j = p1[i] - 1
                    pi, pj = perm[i], perm[j]
                    return perm.at[i].set(pj).at[j].set(pi)

                perm = jax.lax.fori_loop(0, p1.shape[0], body, perm)
                # rows permuted by `perm` give L@U, so A = P @ L @ U with
                # P the inverse (= transpose) of that row permutation
                return jnp.eye(m, dtype=pdtype)[perm].T

            batch = pv.shape[:-1]
            if batch:
                out = jax.vmap(one)(pv.reshape((-1, pv.shape[-1])))
                return out.reshape(tuple(batch) + (m, m))
            return one(pv)

        P = apply(perm_mat, piv, name="lu_unpack_P", differentiable=False)
    return P, L, U


def cdist(x, y, p=2.0, compute_mode="use_mm_for_euclid_dist_if_necessary",
          name=None):
    """Pairwise p-norm distance between rows of x [..., M, D] and
    y [..., N, D]."""
    def fn(a, b):
        if p == 2.0 and compute_mode != "donot_use_mm_for_euclid_dist":
            # MXU path: |a-b|^2 = |a|^2 + |b|^2 - 2ab
            a2 = jnp.sum(a * a, -1)[..., :, None]
            b2 = jnp.sum(b * b, -1)[..., None, :]
            ab = jnp.matmul(a, jnp.swapaxes(b, -1, -2))
            return jnp.sqrt(jnp.maximum(a2 + b2 - 2.0 * ab, 0.0))
        diff = jnp.abs(a[..., :, None, :] - b[..., None, :, :])
        if p == 0:
            return jnp.sum((diff != 0).astype(a.dtype), -1)
        if jnp.isinf(p):
            return jnp.max(diff, -1)
        return jnp.sum(diff ** p, -1) ** (1.0 / p)
    return apply(fn, as_tensor(x), as_tensor(y), name="cdist")


def vecdot(x, y, axis=-1, name=None):
    return apply(lambda a, b: jnp.sum(a * b, axis=axis),
                 as_tensor(x), as_tensor(y), name="vecdot")


def ormqr(x, tau, y, left=True, transpose=False, name=None):
    """Multiply y by the orthogonal Q of the Householder factorization
    (x, tau)."""
    q = householder_product(x, tau)

    def fn(qa, b):
        qm = jnp.swapaxes(qa, -1, -2) if transpose else qa
        return jnp.matmul(qm, b) if left else jnp.matmul(b, qm)
    return apply(fn, q, as_tensor(y), name="ormqr")


def _lowrank_svd(a, q, niter):
    """Randomized range finder + small SVD (Halko et al.) — all matmuls, so
    the MXU does the work."""
    n = a.shape[-1]
    key = jax.random.PRNGKey(0)
    omega = jax.random.normal(key, a.shape[:-2] + (n, q), dtype=a.dtype)
    y = jnp.matmul(a, omega)
    for _ in range(niter):
        y = jnp.matmul(a, jnp.matmul(jnp.swapaxes(a, -1, -2), y))
    Q, _ = jnp.linalg.qr(y)
    B = jnp.matmul(jnp.swapaxes(Q, -1, -2), a)
    u, s, vh = jnp.linalg.svd(B, full_matrices=False)
    return jnp.matmul(Q, u), s, jnp.swapaxes(vh, -1, -2)


def svd_lowrank(x, q=6, niter=2, M=None, name=None):
    xt = as_tensor(x)
    qq = min(q, int(xt.shape[-2]), int(xt.shape[-1]))

    def fn(a, *rest):
        if rest:
            a = a - rest[0]
        return _lowrank_svd(a, qq, niter)

    args = (xt,) if M is None else (xt, as_tensor(M))
    return apply(fn, *args, n_outputs=3, name="svd_lowrank")


def pca_lowrank(x, q=None, center=True, niter=2, name=None):
    xt = as_tensor(x)
    if q is None:
        q = min(6, int(xt.shape[-2]), int(xt.shape[-1]))

    def fn(a):
        if center:
            a = a - jnp.mean(a, axis=-2, keepdims=True)
        return _lowrank_svd(a, q, niter)

    return apply(fn, xt, n_outputs=3, name="pca_lowrank")


__all__ = [
    "cholesky_inverse", "pdist",
    "cholesky", "cholesky_solve", "cond", "corrcoef", "cov", "det", "eig",
    "eigh", "eigvals", "eigvalsh", "householder_product", "inv", "lstsq",
    "lu", "lu_unpack", "matmul", "matrix_exp", "matrix_norm", "matrix_power",
    "matrix_rank", "matrix_transpose", "multi_dot", "norm", "ormqr",
    "pca_lowrank", "pinv", "qr", "slogdet", "solve", "svd", "svd_lowrank",
    "svdvals", "triangular_solve", "vector_norm", "vecdot", "cdist",
]

def svdvals(x, name=None):
    """Singular values only (descending) — no U/V computation."""
    def f(a):
        return jnp.linalg.svd(a, compute_uv=False)

    return apply(f, as_tensor(x), name="svdvals")
