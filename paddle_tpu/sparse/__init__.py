"""``paddle.sparse`` — COO/CSR tensors (python/paddle/sparse/ parity,
UNVERIFIED). Backed by jax.experimental.sparse (BCOO) where it matters;
round-1 scope: creation/conversion + matmul/add."""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from ..framework.core import Tensor
from ..ops.common import as_tensor

__all__ = ["sparse_coo_tensor", "sparse_csr_tensor", "SparseCooTensor",
           "matmul", "add"]


class SparseCooTensor:
    def __init__(self, indices, values, shape):
        self.indices_ = as_tensor(indices)
        self.values_ = as_tensor(values)
        self.shape = list(shape)

    def indices(self):
        return self.indices_

    def values(self):
        return self.values_

    def to_dense(self):
        out = np.zeros(self.shape,
                       dtype=np.asarray(self.values_._data).dtype)
        idx = np.asarray(self.indices_._data)
        vals = np.asarray(self.values_._data)
        out[tuple(idx)] = vals
        return Tensor(jnp.asarray(out))

    def is_sparse(self):
        return True


class SparseCsrTensor:
    def __init__(self, crows, cols, values, shape):
        self.crows_ = as_tensor(crows)
        self.cols_ = as_tensor(cols)
        self.values_ = as_tensor(values)
        self.shape = list(shape)

    def to_dense(self):
        crows = np.asarray(self.crows_._data)
        cols = np.asarray(self.cols_._data)
        vals = np.asarray(self.values_._data)
        out = np.zeros(self.shape, dtype=vals.dtype)
        for r in range(len(crows) - 1):
            for j in range(crows[r], crows[r + 1]):
                out[r, cols[j]] = vals[j]
        return Tensor(jnp.asarray(out))


def sparse_coo_tensor(indices, values, shape=None, dtype=None, place=None,
                      stop_gradient=True):
    if shape is None:
        idx = np.asarray(as_tensor(indices)._data)
        shape = (idx.max(axis=1) + 1).tolist()
    return SparseCooTensor(indices, values, shape)


def sparse_csr_tensor(crows, cols, values, shape, dtype=None, place=None,
                      stop_gradient=True):
    return SparseCsrTensor(crows, cols, values, shape)


def matmul(x, y, name=None):
    xd = x.to_dense() if isinstance(x, (SparseCooTensor, SparseCsrTensor)) \
        else as_tensor(x)
    yd = y.to_dense() if isinstance(y, (SparseCooTensor, SparseCsrTensor)) \
        else as_tensor(y)
    from ..ops.linalg import matmul as mm
    return mm(xd, yd)


def add(x, y, name=None):
    xd = x.to_dense() if isinstance(x, (SparseCooTensor, SparseCsrTensor)) \
        else as_tensor(x)
    yd = y.to_dense() if isinstance(y, (SparseCooTensor, SparseCsrTensor)) \
        else as_tensor(y)
    return xd + yd
