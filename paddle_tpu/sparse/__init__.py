"""``paddle.sparse`` — COO/CSR tensors + sparse ops
(python/paddle/sparse/ parity, UNVERIFIED; reference: SURVEY.md §2.2
"paddle.sparse" row — COO/CSR tensors, sparse conv/attention ops; PHI
sparse kernels in §2.1).

TPU-native: COO is backed by ``jax.experimental.sparse.BCOO`` so sparse
matmul lowers to XLA gather/scatter+dot (not a python loop), and values
participate in the framework's autograd through ``apply`` — gradients
flow to the value array, with the sparsity pattern static (the same
contract the reference's sparse kernels have). CSR keeps the compressed
layout for API parity and converts to COO for compute.
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp
from jax.experimental import sparse as jsparse

from ..framework.core import Tensor, apply
from ..ops.common import as_tensor

__all__ = ["sparse_coo_tensor", "sparse_csr_tensor", "SparseCooTensor",
           "SparseCsrTensor", "matmul", "masked_matmul", "mv", "add",
           "multiply", "subtract", "divide", "is_same_shape", "relu",
           "tanh", "sin", "abs", "sqrt", "pow", "neg", "coalesce",
           "transpose", "nn", "tan", "asin", "atan", "sinh", "asinh",
           "atanh", "square", "log1p", "expm1", "deg2rad", "rad2deg",
           "addmm"]


def _jx(x):
    if isinstance(x, Tensor):
        return x.jax()
    return jnp.asarray(x)


class SparseCooTensor:
    """COO tensor: ``indices`` [ndim, nnz], ``values`` [nnz]."""

    def __init__(self, indices, values, shape):
        self.indices_ = as_tensor(indices)
        self.values_ = as_tensor(values)
        self.shape = list(int(s) for s in shape)

    # -- paddle API --------------------------------------------------------
    def indices(self):
        return self.indices_

    def values(self):
        return self.values_

    @property
    def nnz(self):
        return int(self.values_.shape[0])

    @property
    def dtype(self):
        return self.values_.dtype

    def is_sparse(self):
        return True

    def is_sparse_coo(self):
        return True

    def is_sparse_csr(self):
        return False

    def _bcoo(self, values=None):
        v = self.values_.jax() if values is None else values
        idx = self.indices_.jax().T  # BCOO wants [nnz, ndim]
        return jsparse.BCOO((v, idx), shape=tuple(self.shape))

    def to_dense(self):
        def fn(v):
            idx = self.indices_.jax().T
            return jsparse.BCOO(
                (v, idx), shape=tuple(self.shape)).todense()
        return apply(fn, self.values_, name="sparse_to_dense")

    def to_sparse_csr(self):
        """2-D only; rows must be sorted (coalesce() first if unsure)."""
        if len(self.shape) != 2:
            raise ValueError("to_sparse_csr: 2-D tensors only")
        idx = np.asarray(self.indices_.jax())
        order = np.lexsort((idx[1], idx[0]))
        rows, cols = idx[0][order], idx[1][order]
        vals = self.values_.jax()[jnp.asarray(order)]
        crows = np.zeros(self.shape[0] + 1, np.int64)
        np.add.at(crows, rows + 1, 1)
        crows = np.cumsum(crows)
        return SparseCsrTensor(crows, cols, Tensor(vals), self.shape)

    def coalesce(self):
        """Sort indices, sum duplicates (static nnz shrink)."""
        idx = np.asarray(self.indices_.jax())
        keys = np.ravel_multi_index(tuple(idx), tuple(self.shape))
        uniq, inv = np.unique(keys, return_inverse=True)
        new_idx = np.stack(np.unravel_index(uniq, tuple(self.shape)))

        def fn(v):
            return jax.ops.segment_sum(v, jnp.asarray(inv),
                                       num_segments=len(uniq))
        vals = apply(fn, self.values_, name="sparse_coalesce")
        return SparseCooTensor(Tensor(jnp.asarray(new_idx)), vals,
                               self.shape)

    def transpose(self, perm):
        idx = self.indices_.jax()[jnp.asarray(list(perm))]
        shape = [self.shape[p] for p in perm]
        return SparseCooTensor(Tensor(idx), self.values_, shape)

    def _apply_values(self, fn, name):
        return SparseCooTensor(self.indices_,
                               apply(fn, self.values_, name=name),
                               self.shape)

    def __repr__(self):
        return (f"SparseCooTensor(shape={self.shape}, nnz={self.nnz}, "
                f"dtype={self.dtype})")


class SparseCsrTensor:
    """CSR tensor (2-D): crows [rows+1], cols [nnz], values [nnz]."""

    def __init__(self, crows, cols, values, shape):
        self.crows_ = as_tensor(crows)
        self.cols_ = as_tensor(cols)
        self.values_ = as_tensor(values)
        self.shape = list(int(s) for s in shape)

    def crows(self):
        return self.crows_

    def cols(self):
        return self.cols_

    def values(self):
        return self.values_

    @property
    def nnz(self):
        return int(self.values_.shape[0])

    @property
    def dtype(self):
        return self.values_.dtype

    def is_sparse(self):
        return True

    def is_sparse_coo(self):
        return False

    def is_sparse_csr(self):
        return True

    def to_sparse_coo(self, sparse_dim=2):
        crows = np.asarray(self.crows_.jax())
        rows = np.repeat(np.arange(len(crows) - 1), np.diff(crows))
        idx = np.stack([rows, np.asarray(self.cols_.jax())])
        return SparseCooTensor(Tensor(jnp.asarray(idx)), self.values_,
                               self.shape)

    def to_dense(self):
        return self.to_sparse_coo().to_dense()

    def __repr__(self):
        return (f"SparseCsrTensor(shape={self.shape}, nnz={self.nnz}, "
                f"dtype={self.dtype})")


# --------------------------------------------------------------------------
# creation
# --------------------------------------------------------------------------

def sparse_coo_tensor(indices, values, shape=None, dtype=None, place=None,
                      stop_gradient=True):
    idx_t = as_tensor(indices)
    val_t = as_tensor(values)
    if dtype is not None:
        val_t = val_t.astype(dtype)
    if shape is None:
        idx = np.asarray(idx_t.jax())
        shape = (idx.max(axis=1) + 1).tolist()
    return SparseCooTensor(idx_t, val_t, shape)


def sparse_csr_tensor(crows, cols, values, shape, dtype=None, place=None,
                      stop_gradient=True):
    val_t = as_tensor(values)
    if dtype is not None:
        val_t = val_t.astype(dtype)
    return SparseCsrTensor(crows, cols, val_t, shape)


def _as_coo(x):
    if isinstance(x, SparseCsrTensor):
        return x.to_sparse_coo()
    return x


def is_same_shape(x, y):
    xs = x.shape if isinstance(x, (SparseCooTensor, SparseCsrTensor)) \
        else list(x.shape)
    ys = y.shape if isinstance(y, (SparseCooTensor, SparseCsrTensor)) \
        else list(y.shape)
    return list(xs) == list(ys)


# --------------------------------------------------------------------------
# compute
# --------------------------------------------------------------------------

def matmul(x, y, name=None):
    """sparse @ dense -> dense (XLA-lowered BCOO contraction);
    dense @ dense passes through; sparse @ sparse densifies y."""
    if isinstance(x, (SparseCooTensor, SparseCsrTensor)):
        xc = _as_coo(x)
        yd = y.to_dense() if isinstance(
            y, (SparseCooTensor, SparseCsrTensor)) else as_tensor(y)

        def fn(v, d):
            return xc._bcoo(v) @ d
        return apply(fn, xc.values_, yd, name="sparse_matmul")
    from ..ops.linalg import matmul as mm
    yd = y.to_dense() if isinstance(
        y, (SparseCooTensor, SparseCsrTensor)) else y
    return mm(as_tensor(x), yd)


def mv(x, vec, name=None):
    return matmul(x, vec, name=name)


def masked_matmul(x, y, mask, name=None):
    """(x @ y) sampled at mask's sparsity pattern (SDDMM) -> sparse with
    mask's pattern. x, y dense; mask sparse."""
    mc = _as_coo(mask)
    xd, yd = as_tensor(x), as_tensor(y)

    def fn(a, b):
        rows = mc.indices_.jax()[0]
        cols = mc.indices_.jax()[1]
        # gather the needed rows/cols; one dot per nnz, vectorized
        return jnp.einsum("nk,nk->n", a[rows], b[:, cols].T)
    vals = apply(fn, xd, yd, name="masked_matmul")
    return SparseCooTensor(mc.indices_, vals, mc.shape)


def add(x, y, name=None):
    xs = isinstance(x, (SparseCooTensor, SparseCsrTensor))
    ys = isinstance(y, (SparseCooTensor, SparseCsrTensor))
    if xs and ys:
        xc, yc = _as_coo(x), _as_coo(y)
        if xc.shape != yc.shape:
            raise ValueError("sparse add: shape mismatch")
        idx = Tensor(jnp.concatenate(
            [xc.indices_.jax(), yc.indices_.jax()], axis=1))

        def fn(a, b):
            return jnp.concatenate([a, b])
        vals = apply(fn, xc.values_, yc.values_, name="sparse_add")
        return SparseCooTensor(idx, vals, xc.shape).coalesce()
    if xs or ys:
        sp, de = (x, y) if xs else (y, x)
        return _as_coo(sp).to_dense() + as_tensor(de)
    return as_tensor(x) + as_tensor(y)


def subtract(x, y, name=None):
    yc = _as_coo(y) if isinstance(
        y, (SparseCooTensor, SparseCsrTensor)) else y
    if isinstance(yc, SparseCooTensor):
        yn = yc._apply_values(lambda v: -v, "sparse_neg")
        return add(x, yn, name=name)
    return add(x, as_tensor(yc) * -1.0, name=name)


def multiply(x, y, name=None):
    """Elementwise; sparse * scalar/dense keeps the sparse pattern."""
    if isinstance(x, (SparseCooTensor, SparseCsrTensor)):
        xc = _as_coo(x)
        if isinstance(y, (int, float)):
            return xc._apply_values(lambda v: v * y, "sparse_scale")
        yt = (y.to_dense() if isinstance(
            y, (SparseCooTensor, SparseCsrTensor)) else as_tensor(y))
        rows_cols = tuple(xc.indices_.jax())
        vals = apply(lambda v, d: v * d[rows_cols],
                     xc.values_, yt, name="sparse_mul")
        return SparseCooTensor(xc.indices_, vals, xc.shape)
    return as_tensor(x) * y


def divide(x, y, name=None):
    if isinstance(x, (SparseCooTensor, SparseCsrTensor)) and \
            isinstance(y, (int, float)):
        return _as_coo(x)._apply_values(lambda v: v / y, "sparse_div")
    return multiply(x, 1.0 / y, name=name)


def coalesce(x, name=None):
    return _as_coo(x).coalesce()


def transpose(x, perm, name=None):
    return _as_coo(x).transpose(perm)


# unary ops on values (zero-preserving set, paddle.sparse convention)
def _unary(jfn, pyname):
    def op(x, name=None):
        if isinstance(x, (SparseCooTensor, SparseCsrTensor)):
            return _as_coo(x)._apply_values(jfn, f"sparse_{pyname}")
        return apply(jfn, as_tensor(x), name=pyname)
    op.__name__ = pyname
    return op


relu = _unary(lambda v: jnp.maximum(v, 0), "relu")
tanh = _unary(jnp.tanh, "tanh")
sin = _unary(jnp.sin, "sin")
abs = _unary(jnp.abs, "abs")
sqrt = _unary(jnp.sqrt, "sqrt")
neg = _unary(lambda v: -v, "neg")
# the rest of paddle.sparse's zero-preserving unary set
tan = _unary(jnp.tan, "tan")
asin = _unary(jnp.arcsin, "asin")
atan = _unary(jnp.arctan, "atan")
sinh = _unary(jnp.sinh, "sinh")
asinh = _unary(jnp.arcsinh, "asinh")
atanh = _unary(jnp.arctanh, "atanh")
square = _unary(jnp.square, "square")
log1p = _unary(jnp.log1p, "log1p")
expm1 = _unary(jnp.expm1, "expm1")
deg2rad = _unary(jnp.deg2rad, "deg2rad")
rad2deg = _unary(jnp.rad2deg, "rad2deg")


def addmm(input, x, y, beta=1.0, alpha=1.0, name=None):
    """paddle.sparse.addmm — beta*input + alpha*(x @ y); x sparse (COO or
    CSR), input/y dense."""
    prod = matmul(x, y)
    return apply(lambda i, m: beta * i + alpha * m, as_tensor(input),
                 as_tensor(prod), name="sparse_addmm")


def pow(x, factor, name=None):
    if isinstance(x, (SparseCooTensor, SparseCsrTensor)):
        return _as_coo(x)._apply_values(lambda v: v ** factor,
                                        "sparse_pow")
    return apply(lambda v: v ** factor, as_tensor(x), name="pow")


from . import nn  # noqa: E402 — layers need the ops above


# --------------------------------------------------------------------------
# round-3 long tail: cast / isnan / sum / reshape / slice / mask_as
# --------------------------------------------------------------------------

def cast(x, index_dtype=None, value_dtype=None, name=None):
    """paddle.sparse.cast parity: cast indices and/or values."""
    from ..framework.core import to_jax_dtype
    xc = _as_coo(x)
    idx = xc.indices_
    if index_dtype is not None:
        idx = Tensor(idx.jax().astype(to_jax_dtype(index_dtype)))
    vals = xc.values_
    if value_dtype is not None:
        vals = apply(lambda v: v.astype(to_jax_dtype(value_dtype)), vals,
                     name="sparse_cast")
    out = SparseCooTensor(idx, vals, xc.shape)
    if isinstance(x, SparseCsrTensor) and len(xc.shape) == 2:
        out = out.to_sparse_csr()
        if index_dtype is not None:
            # the round-trip rebuilds crows as int64 — apply the
            # requested index dtype to BOTH compressed arrays
            jdt = to_jax_dtype(index_dtype)
            out.crows_ = Tensor(out.crows_.jax().astype(jdt))
            out.cols_ = Tensor(out.cols_.jax().astype(jdt))
    return out


def isnan(x, name=None):
    """Elementwise isnan on the stored values (pattern unchanged)."""
    xc = _as_coo(x)
    return xc._apply_values(jnp.isnan, "sparse_isnan")


def sum(x, axis=None, dtype=None, keepdim=False, name=None):
    """paddle.sparse.sum: reduce over all dims → dense scalar; over one
    axis → sparse result with that dim dropped (or kept size-1)."""
    from ..framework.core import to_jax_dtype
    xc = _as_coo(x)
    cast_to = None if dtype is None else to_jax_dtype(dtype)
    if axis is None:
        return apply(lambda v: jnp.sum(
            v if cast_to is None else v.astype(cast_to)), xc.values_,
            name="sparse_sum")
    ax = int(axis) % len(xc.shape)
    idx = np.asarray(xc.indices_.jax())
    rest = [i for i in range(len(xc.shape)) if i != ax]
    if not rest:  # 1-D: scalar-per-pattern → dense 0-d / size-1
        return apply(lambda v: jnp.sum(
            v if cast_to is None else v.astype(cast_to),
            keepdims=keepdim), xc.values_, name="sparse_sum")
    rest_shape = [xc.shape[i] for i in rest]
    keys = np.ravel_multi_index(tuple(idx[rest]), tuple(rest_shape))
    uniq, inv = np.unique(keys, return_inverse=True)
    new_idx = np.stack(np.unravel_index(uniq, tuple(rest_shape)))

    def fn(v):
        if cast_to is not None:
            v = v.astype(cast_to)
        return jax.ops.segment_sum(v, jnp.asarray(inv),
                                   num_segments=len(uniq))
    vals = apply(fn, xc.values_, name="sparse_sum")
    shape = rest_shape
    if keepdim:
        new_idx = np.insert(new_idx, ax, 0, axis=0)
        shape = list(xc.shape)
        shape[ax] = 1
    out = SparseCooTensor(Tensor(jnp.asarray(new_idx)), vals, shape)
    return out.to_sparse_csr() if isinstance(x, SparseCsrTensor) and \
        len(shape) == 2 else out


def reshape(x, shape, name=None):
    """Reshape a sparse COO tensor: indices re-derived through the flat
    ravel order (values untouched — autograd flows)."""
    xc = _as_coo(x)
    shape = [int(s) for s in shape]
    size = int(np.prod(xc.shape))
    neg = [i for i, s in enumerate(shape) if s == -1]
    if neg:
        known = -int(np.prod(shape))
        shape[neg[0]] = size // known
    if int(np.prod(shape)) != size:
        raise ValueError(f"sparse.reshape: cannot reshape {xc.shape} "
                         f"into {shape}")
    idx = np.asarray(xc.indices_.jax())
    flat = np.ravel_multi_index(tuple(idx), tuple(xc.shape))
    new_idx = np.stack(np.unravel_index(flat, tuple(shape)))
    out = SparseCooTensor(Tensor(jnp.asarray(new_idx)), xc.values_,
                          shape)
    return out.to_sparse_csr() if isinstance(x, SparseCsrTensor) and \
        len(shape) == 2 else out


def slice(x, axes, starts, ends, name=None):
    """paddle.sparse.slice parity: keep entries inside [start, end) per
    sliced axis, shift indices (host-side pattern op; values keep
    autograd via a gather)."""
    xc = _as_coo(x)
    idx = np.asarray(xc.indices_.jax())
    shape = list(xc.shape)
    def _resolve(st, dim):
        st = int(st) if st >= 0 else int(st) + dim
        return min(max(st, 0), dim)  # clamp like dense paddle.slice

    keep = np.ones(idx.shape[1], bool)
    for ax, st, en in zip(axes, starts, ends):
        ax = int(ax) % len(shape)
        st = _resolve(st, shape[ax])
        en = _resolve(en, shape[ax])
        keep &= (idx[ax] >= st) & (idx[ax] < en)
        shape[ax] = max(en - st, 0)
    sel = np.flatnonzero(keep)
    new_idx = idx[:, sel].copy()
    for ax, st, _ in zip(axes, starts, ends):
        ax = int(ax) % len(xc.shape)
        new_idx[ax] -= _resolve(st, xc.shape[ax])
    vals = apply(lambda v: v[jnp.asarray(sel)], xc.values_,
                 name="sparse_slice")
    out = SparseCooTensor(Tensor(jnp.asarray(new_idx)), vals, shape)
    return out.to_sparse_csr() if isinstance(x, SparseCsrTensor) and \
        len(shape) == 2 else out


def mask_as(x, mask, name=None):
    """paddle.sparse.mask_as: take dense ``x``'s entries at ``mask``'s
    sparsity pattern."""
    m = _as_coo(mask)
    idx = m.indices_.jax()

    def fn(d):
        return d[tuple(idx[i] for i in range(idx.shape[0]))]
    vals = apply(fn, as_tensor(x), name="sparse_mask_as")
    out = SparseCooTensor(m.indices_, vals, m.shape)
    return out.to_sparse_csr() if isinstance(mask, SparseCsrTensor) and \
        len(m.shape) == 2 else out


def relu6(x, name=None):
    xc = _as_coo(x)
    return xc._apply_values(lambda v: jnp.clip(v, 0.0, 6.0),
                            "sparse_relu6")


def leaky_relu(x, negative_slope=0.01, name=None):
    xc = _as_coo(x)
    return xc._apply_values(
        lambda v: jnp.where(v >= 0, v, negative_slope * v),
        "sparse_leaky_relu")


__all__ += ["cast", "isnan", "sum", "reshape", "slice", "mask_as",
            "relu6", "leaky_relu"]
