"""``paddle.sparse.nn`` — layers over sparse COO tensors (upstream
python/paddle/sparse/nn/, UNVERIFIED; SURVEY.md §2.2 paddle.sparse row;
PHI sparse conv kernels in §2.1).

TPU-native stance: XLA has no sparse-conv HLO, and on TPU the MXU wants
dense tiles — so the convolutions here are DENSE-COMPUTE with a
structural occupancy pattern: densify the active sites, run
``lax.conv_general_dilated`` (channels-last, the sparse-world layout),
and re-sparsify at the structurally-reachable output sites (Conv*) or
the input's own sites (SubmConv*, the submanifold contract). Pattern
bookkeeping is host-side eager (patterns are data prep); the value
compute path is jax-differentiable end to end."""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

from ..framework.core import Tensor, apply
from ..nn.layer.layers import Layer
from ..nn import initializer as I
from . import SparseCooTensor, _as_coo

__all__ = ["ReLU", "ReLU6", "LeakyReLU", "Softmax", "BatchNorm",
           "SyncBatchNorm", "Conv2D", "Conv3D", "SubmConv2D",
           "SubmConv3D", "MaxPool3D", "functional"]


class ReLU(Layer):
    def forward(self, x):
        from . import relu
        return relu(x)


class ReLU6(Layer):
    def forward(self, x):
        from . import relu6
        return relu6(x)


class LeakyReLU(Layer):
    def __init__(self, negative_slope=0.01):
        super().__init__()
        self.negative_slope = negative_slope

    def forward(self, x):
        from . import leaky_relu
        return leaky_relu(x, self.negative_slope)


class Softmax(Layer):
    """Row-wise softmax over a 2-D sparse pattern."""

    def __init__(self, axis=-1):
        super().__init__()
        if axis != -1:
            raise NotImplementedError("sparse softmax: axis=-1 only")

    def forward(self, x):
        xc = _as_coo(x)
        rows = xc.indices_.jax()[0]
        n_rows = xc.shape[0]

        def fn(v):
            rmax = jax.ops.segment_max(v, rows, num_segments=n_rows)
            e = jnp.exp(v - rmax[rows])
            rsum = jax.ops.segment_sum(e, rows, num_segments=n_rows)
            return e / rsum[rows]
        return xc._apply_values(fn, "sparse_softmax")


class BatchNorm(Layer):
    """BatchNorm over sparse values [nnz, C]: per-channel statistics of
    the STORED entries (the sparse-conv convention — implicit zeros do
    not contribute), running stats for eval."""

    def __init__(self, num_features, momentum=0.9, epsilon=1e-5,
                 weight_attr=None, bias_attr=None, data_format="NDHWC",
                 use_global_stats=None, name=None):
        super().__init__()
        self.momentum = momentum
        self.epsilon = epsilon
        self.use_global_stats = use_global_stats
        self.weight = self.create_parameter(
            [num_features], attr=weight_attr,
            default_initializer=I.Constant(1.0))
        self.bias = self.create_parameter(
            [num_features], attr=bias_attr, is_bias=True,
            default_initializer=I.Constant(0.0))
        self.register_buffer("_mean", Tensor(
            jnp.zeros((num_features,), jnp.float32)))
        self.register_buffer("_variance", Tensor(
            jnp.ones((num_features,), jnp.float32)))

    def forward(self, x):
        xc = _as_coo(x)
        training = self.training and not self.use_global_stats
        w, b = self.weight, self.bias
        eps, mom = self.epsilon, self.momentum
        rm, rv = self._mean, self._variance
        n_ch = int(w.shape[0])
        # fully-sparse layout: the channel is the LAST index row and
        # values are flat [nnz] — per-channel stats are segment reduces.
        # The NORMALIZATION stats must be computed inside the traced fn
        # so backward carries the d(mean)/dv and d(var)/dv terms (real
        # train-mode BN semantics); the RUNNING buffers are a host-side
        # bincount over the same values — a cheap O(nnz) numpy pass
        # (these pattern layers are eager ops; patterns are host data).
        ch = xc.indices_.jax()[-1]

        def fn(v, wj, bj):
            if training:
                cnt = jnp.clip(jax.ops.segment_sum(
                    jnp.ones_like(v), ch, num_segments=n_ch), 1.0, None)
                mean = jax.ops.segment_sum(
                    v, ch, num_segments=n_ch) / cnt
                varb = jax.ops.segment_sum(
                    (v - mean[ch]) ** 2, ch, num_segments=n_ch) / cnt
            else:
                mean, varb = rm._data, rv._data
            return (v - mean[ch]) / jnp.sqrt(varb[ch] + eps) * wj[ch] \
                + bj[ch]
        out = apply(fn, xc.values_, w, b, name="sparse_batch_norm")
        if training:
            v = np.asarray(xc.values_.jax(), np.float32)
            chn = np.asarray(ch)
            raw_cnt = np.bincount(chn, minlength=n_ch)
            cnt = np.maximum(raw_cnt, 1)
            mean = np.bincount(chn, weights=v, minlength=n_ch) / cnt
            varb = np.bincount(chn, weights=(v - mean[chn]) ** 2,
                               minlength=n_ch) / cnt
            varb = np.where(raw_cnt > 0, varb, 1.0)
            rm._inplace_update(
                (mom * rm._data
                 + (1 - mom) * jnp.asarray(mean, jnp.float32)))
            rv._inplace_update(
                (mom * rv._data
                 + (1 - mom) * jnp.asarray(varb, jnp.float32)))
        return SparseCooTensor(xc.indices_, out, xc.shape)


class SyncBatchNorm(BatchNorm):
    """Cross-replica BatchNorm: under GSPMD the value statistics are
    computed on the global (unsharded) nnz axis, so plain BatchNorm IS
    sync — kept as a distinct class for API parity."""


def _occupancy(idx, shape):
    dense = np.zeros(shape, np.float32)
    dense[tuple(idx)] = 1.0
    return dense


class _SparseConvND(Layer):
    """Shared dense-compute sparse conv (see module docstring)."""

    def __init__(self, nd, in_channels, out_channels, kernel_size,
                 stride, padding, dilation, groups, subm,
                 weight_attr=None, bias_attr=None):
        super().__init__()
        if groups != 1:
            raise NotImplementedError("sparse conv: groups=1 only")
        to_tup = (lambda v: (v,) * nd if isinstance(v, int)
                  else tuple(v))
        self.nd = nd
        self.kernel_size = to_tup(kernel_size)
        self.stride = to_tup(1) if subm else to_tup(stride)
        self.dilation = to_tup(dilation)
        self.subm = subm
        if subm:
            # submanifold semantics: output pattern == input pattern,
            # each site aggregating its CENTERED kernel window — which
            # requires same-centered padding regardless of the
            # constructor's padding arg (spconv/SECOND behavior; with
            # padding=0 the conv output grid would be smaller than the
            # pattern and the gather would read wrong sites)
            self.padding = tuple(d * (k - 1) // 2 for k, d in
                                 zip(self.kernel_size, self.dilation))
        else:
            self.padding = to_tup(padding)
        fan_in = in_channels * int(np.prod(self.kernel_size))
        bound = 1.0 / fan_in ** 0.5
        # channels-last kernel [*k, in, out] — the sparse-world layout
        self.weight = self.create_parameter(
            [*self.kernel_size, in_channels, out_channels],
            attr=weight_attr, default_initializer=I.Uniform(-bound, bound))
        self.bias = None if bias_attr is False else self.create_parameter(
            [out_channels], attr=bias_attr, is_bias=True,
            default_initializer=I.Uniform(-bound, bound))

    def _dims(self):
        if self.nd == 2:
            return ("NHWC", "HWIO", "NHWC")
        return ("NDHWC", "DHWIO", "NDHWC")

    def forward(self, x):
        xc = _as_coo(x)
        idx = np.asarray(xc.indices_.jax())
        shape = tuple(xc.shape)          # [N, *spatial, C]
        pad = [(p, p) for p in self.padding]
        dims = jax.lax.conv_dimension_numbers(
            (1,) + shape[1:], tuple(self.weight.shape), self._dims())

        def fn(v, w, *rest):
            dense = jnp.zeros(shape, v.dtype)
            dense = dense.at[tuple(idx)].set(v)
            out = jax.lax.conv_general_dilated(
                dense, w, window_strides=self.stride, padding=pad,
                rhs_dilation=self.dilation, dimension_numbers=dims)
            if rest:
                out = out + rest[0]
            return out

        args = [xc.values_, self.weight]
        if self.bias is not None:
            args.append(self.bias)
        dense_out = apply(fn, *args, name="sparse_conv")

        out_ch = int(self.weight.shape[-1])
        if self.subm:
            # submanifold: output SPATIAL pattern == input spatial
            # pattern (dedup the per-channel rows), all out channels
            sites = np.unique(idx[:-1].T, axis=0)
            out_shape = shape[:-1] + (out_ch,)
        else:
            # structural occupancy: which output sites see any input site
            occ = _occupancy(idx[:-1], shape[:-1])[..., None]
            ones = np.ones(tuple(self.kernel_size) + (1, 1), np.float32)
            reach = jax.lax.conv_general_dilated(
                jnp.asarray(occ), jnp.asarray(ones),
                window_strides=self.stride, padding=pad,
                rhs_dilation=self.dilation,
                dimension_numbers=jax.lax.conv_dimension_numbers(
                    occ.shape, ones.shape, self._dims()))
            sites = np.argwhere(np.asarray(reach)[..., 0] > 0)
            out_shape = tuple(int(s) for s in reach.shape[:-1]) \
                + (out_ch,)
        # fully-sparse output: channel is an index row, values are flat
        out_idx = np.concatenate(
            [np.repeat(sites, out_ch, 0),
             np.tile(np.arange(out_ch), len(sites))[:, None]], axis=1).T
        vals = apply(
            lambda d: d[tuple(jnp.asarray(out_idx[i])
                              for i in range(out_idx.shape[0]))],
            dense_out, name="sparse_conv_gather")
        return SparseCooTensor(Tensor(jnp.asarray(out_idx)), vals,
                               list(out_shape))


class Conv2D(_SparseConvND):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, groups=1, padding_mode="zeros",
                 weight_attr=None, bias_attr=None, data_format="NHWC"):
        super().__init__(2, in_channels, out_channels, kernel_size,
                         stride, padding, dilation, groups, subm=False,
                         weight_attr=weight_attr, bias_attr=bias_attr)


class SubmConv2D(_SparseConvND):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, groups=1, padding_mode="zeros",
                 key=None, weight_attr=None, bias_attr=None,
                 data_format="NHWC"):
        super().__init__(2, in_channels, out_channels, kernel_size,
                         stride, padding, dilation, groups, subm=True,
                         weight_attr=weight_attr, bias_attr=bias_attr)


class Conv3D(_SparseConvND):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, groups=1, padding_mode="zeros",
                 weight_attr=None, bias_attr=None, data_format="NDHWC"):
        super().__init__(3, in_channels, out_channels, kernel_size,
                         stride, padding, dilation, groups, subm=False,
                         weight_attr=weight_attr, bias_attr=bias_attr)


class SubmConv3D(_SparseConvND):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, groups=1, padding_mode="zeros",
                 key=None, weight_attr=None, bias_attr=None,
                 data_format="NDHWC"):
        super().__init__(3, in_channels, out_channels, kernel_size,
                         stride, padding, dilation, groups, subm=True,
                         weight_attr=weight_attr, bias_attr=bias_attr)


class MaxPool3D(Layer):
    """Sparse max pooling on [N, D, H, W, C]: dense window reduce over
    the active sites (implicit zeros excluded via -inf fill), output at
    the structurally-occupied pooled sites."""

    def __init__(self, kernel_size, stride=None, padding=0,
                 ceil_mode=False, return_mask=False, data_format="NDHWC",
                 name=None):
        super().__init__()
        if ceil_mode or return_mask:
            raise NotImplementedError(
                "sparse MaxPool3D: ceil_mode/return_mask not supported")
        to_tup = (lambda v: (v,) * 3 if isinstance(v, int) else tuple(v))
        self.kernel = to_tup(kernel_size)
        self.stride = to_tup(stride if stride is not None else kernel_size)
        self.padding = to_tup(padding)

    def forward(self, x):
        xc = _as_coo(x)
        idx = np.asarray(xc.indices_.jax())
        shape = tuple(xc.shape)
        window = (1,) + self.kernel + (1,)
        strides = (1,) + self.stride + (1,)
        pads = ((0, 0),) + tuple((p, p) for p in self.padding) + ((0, 0),)

        def fn(v):
            dense = jnp.full(shape, -jnp.inf, v.dtype)
            dense = dense.at[tuple(idx)].set(v)
            return jax.lax.reduce_window(
                dense, jnp.asarray(-jnp.inf, v.dtype), jax.lax.max,
                window, strides, pads)
        dense_out = apply(fn, xc.values_, name="sparse_maxpool")

        # PER-CHANNEL occupancy (channel window is 1): a channel with no
        # stored entry in a window gets NO output entry — enumerating
        # every channel at each reachable spatial site would gather the
        # -inf fill
        occ = _occupancy(idx, shape)
        reach = jax.lax.reduce_window(
            jnp.asarray(occ), np.float32(0), jax.lax.max,
            window, strides, pads)
        out_idx = np.argwhere(np.asarray(reach) > 0).T
        vals = apply(
            lambda d: d[tuple(jnp.asarray(out_idx[i])
                              for i in range(out_idx.shape[0]))],
            dense_out, name="sparse_maxpool_gather")
        out_shape = [int(s) for s in np.asarray(reach).shape]
        return SparseCooTensor(Tensor(jnp.asarray(out_idx)), vals,
                               out_shape)


class _Functional:
    """``paddle.sparse.nn.functional`` — functional mirrors."""

    @staticmethod
    def relu(x, name=None):
        from . import relu as _relu
        return _relu(x)

    @staticmethod
    def relu6(x, name=None):
        from . import relu6 as _relu6
        return _relu6(x)

    @staticmethod
    def leaky_relu(x, negative_slope=0.01, name=None):
        from . import leaky_relu as _lr
        return _lr(x, negative_slope)

    @staticmethod
    def softmax(x, axis=-1, name=None):
        return Softmax(axis)(x)

    @staticmethod
    def attention(query, key, value, sparse_mask, key_padding_mask=None,
                  attn_mask=None, name=None):
        """paddle.sparse.nn.functional.attention parity: q/k/v
        [B, H, S, D], ``sparse_mask`` a CSR tensor giving the attention
        pattern — one shared [S, S] pattern, or [B*H, S, S] batched
        (crows [B*H, S+1] / cols flattened). Bridges to the dense-masked
        ``nn.functional.sparse_attention`` kernel (MXU-friendly)."""
        from ..nn.functional import sparse_attention
        from . import SparseCsrTensor

        if not isinstance(sparse_mask, SparseCsrTensor):
            raise TypeError("sparse_mask must be a SparseCsrTensor")
        b, h = int(query.shape[0]), int(query.shape[1])
        crows = jnp.asarray(sparse_mask.crows_.jax())
        cols = jnp.asarray(sparse_mask.cols_.jax())
        if crows.ndim == 1:  # one shared pattern → broadcast over B, H
            off = jnp.broadcast_to(crows, (b, h) + crows.shape)
            col = jnp.broadcast_to(cols, (b, h) + cols.shape)
        else:  # [B*H, S+1] batched pattern (uniform nnz per head)
            off = crows.reshape(b, h, -1)
            col = cols.reshape(b, h, -1)
        return sparse_attention(query, key, value, Tensor(off),
                                Tensor(col), key_padding_mask, attn_mask)


functional = _Functional()
