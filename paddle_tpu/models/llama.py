"""Llama family (BASELINE configs 2/4: Llama-3-8B single chip, 70B 4D
hybrid) — the flagship model.

TPU-first: RMSNorm + RoPE + flash attention are the Pallas kernel pack
(SURVEY.md §7 step 5); GQA repeats kv heads inside the kernel; weights use
tensor-parallel layers that carry 'model'-axis NamedSharding when fleet is
initialized with mp_degree > 1, and the whole forward is
sharding-constraint-annotated so GSPMD lays out activations."""

from __future__ import annotations

from dataclasses import dataclass

import jax.numpy as jnp

from ..framework.core import Tensor, apply
from ..framework import flags
from ..distributed.communication import in_traced_collective
from .. import nn
from ..nn import functional as F
from ..ops import creation, manipulation as M
from ..ops.linalg import matmul
from ..distributed.parallel_layers import (ColumnParallelLinear,
                                           RowParallelLinear,
                                           VocabParallelEmbedding)
from ..generation import GenerationMixin

__all__ = ["LlamaConfig", "LlamaModel", "LlamaForCausalLM",
           "LlamaForCausalLMPipe", "LlamaPretrainingCriterion"]


@dataclass
class LlamaConfig:
    vocab_size: int = 128256
    hidden_size: int = 4096
    num_hidden_layers: int = 32
    num_attention_heads: int = 32
    num_key_value_heads: int = 8
    intermediate_size: int = 14336
    max_position_embeddings: int = 8192
    rope_theta: float = 500000.0
    rms_norm_eps: float = 1e-5
    initializer_range: float = 0.02
    tie_word_embeddings: bool = False
    use_recompute: bool = False
    # "full" remats whole decoder layers; "core_attn" keeps the flash
    # attention core OUT of the remat region (its custom-vjp forward
    # would otherwise re-run inside backward — ~4% of step FLOPs at
    # S=2048; saving the [B,S,H,D] context costs ~21MB/layer bf16).
    # PaddleNLP's recompute_granularity knob, TPU-tuned semantics.
    recompute_granularity: str = "full"
    # apply core_attn to every Nth layer only (1 = all): doses the saved-
    # context memory against HBM headroom — full-depth 2.4B at interval 1
    # OOMs a 16GB v5e by a few hundred MB, interval 2 fits
    core_attn_interval: int = 1
    # every k-th layer skips remat entirely (activations saved whole);
    # 0 = off — the remat-dose knob for spending leftover HBM on speed
    full_save_interval: int = 0
    tensor_parallel: bool = True  # use TP layers (degenerate w/o mesh)
    # context parallelism over the 'sep' mesh axis:
    # None | "ring" | "ulysses" | "allgather" (gathered-K/V CP — the
    # impl that also runs under the explicit 1F1B/ZB-H1 engines)
    sep_parallel: str | None = None
    # Megatron-style SP: keep LN/residual activations sequence-sharded over
    # the 'model' axis (memory win; XLA inserts the gathers)
    sequence_parallel: bool = False
    # roll the decoder stack into one lax.scan (code-size win on TPU;
    # see nn/scan.py) — turn off to unroll (e.g. heterogeneous stacks)
    scan_layers: bool = True
    # weight-only serving quantization (ISSUE 20): None keeps full
    # precision; "weight_only_int8" / "weight_only_int4" route the big
    # projections (qkv/o/gate/up/down + lm_head) through dequant-in-
    # matmul layers when nn.quant.quantize_for_serving runs at load
    weight_quant: str | None = None

    def __post_init__(self):
        # validate at construction so a typo'd granularity fails where
        # it was written, not only when the unrolled remat path runs
        if self.recompute_granularity not in ("full", "core_attn",
                                              "full_attn"):
            raise ValueError(
                f"recompute_granularity="
                f"{self.recompute_granularity!r} is not one of "
                "'full' | 'core_attn' | 'full_attn'")
        if self.weight_quant not in (None, "weight_only_int8",
                                     "weight_only_int4"):
            raise ValueError(
                f"weight_quant={self.weight_quant!r} is not one of "
                "None | 'weight_only_int8' | 'weight_only_int4'")

    @classmethod
    def llama3_8b(cls):
        return cls()

    @classmethod
    def llama3_70b(cls):
        return cls(hidden_size=8192, num_hidden_layers=80,
                   num_attention_heads=64, num_key_value_heads=8,
                   intermediate_size=28672)

    @classmethod
    def llama_1b(cls):
        """Single-v5e-chip bench config (8B does not fit 16GB HBM for
        training)."""
        return cls(vocab_size=32000, hidden_size=2048,
                   num_hidden_layers=16, num_attention_heads=16,
                   num_key_value_heads=8, intermediate_size=5632,
                   max_position_embeddings=4096, rope_theta=10000.0)

    @classmethod
    def llama_2_4b(cls):
        """Largest-fit v5e training config (2.4B params — NOT the
        Llama-2-7B checkpoint shape): with bf16 params+grads
        (2 x 2.4B x 2B = 9.6GB) plus remat'd activations it fills a 16GB
        chip; 8B (16GB params+grads alone) cannot fit — see BASELINE.md."""
        return cls(vocab_size=32000, hidden_size=2560,
                   num_hidden_layers=32, num_attention_heads=20,
                   num_key_value_heads=4, intermediate_size=6912,
                   max_position_embeddings=4096, rope_theta=10000.0,
                   use_recompute=True,
                   # keep the flash core out of remat: 99.4 vs 103.0 ms
                   # on the L4 tuning slice (v5e); +21MB/layer saved ctx,
                   # dosed to every 2nd layer to fit 16GB HBM. Requires
                   # the unrolled stack (also the faster one on-chip).
                   recompute_granularity="core_attn",
                   core_attn_interval=2,
                   scan_layers=False)

    @classmethod
    def tiny(cls):
        return cls(vocab_size=256, hidden_size=64, num_hidden_layers=2,
                   num_attention_heads=4, num_key_value_heads=2,
                   intermediate_size=128, max_position_embeddings=128,
                   rope_theta=10000.0)

    @property
    def head_dim(self):
        return self.hidden_size // self.num_attention_heads


def rope_with_offset(t, pos, max_pos, theta):
    """RoPE at absolute positions ``pos + [0..S)`` (decode-with-cache path);
    table length is the static ``max_pos`` so the traced offset only picks
    rows."""
    from ..ops.pallas import rope as rope_mod

    def fn(a, p):
        s_tab, c_tab = rope_mod.build_sin_cos(max_pos, a.shape[-1], theta)
        pid = (p.astype(jnp.int32)
               + jnp.arange(a.shape[1], dtype=jnp.int32)[None, :])
        pid = jnp.broadcast_to(pid, (a.shape[0], a.shape[1]))
        return rope_mod.apply_rope(a, s_tab, c_tab, pid)

    return apply(fn, t, pos, name="rope_cached")


def _paged_attention_step(attn, q, k, v, cache, pos, tables, rope=True,
                          proj=None):
    """Continuous-batching step over the PAGED pool, shared by the
    Llama/Qwen2/GPT2 attention layers: per-slot positions (mixed-length
    streams), trash-page routing for drained slots (serving engine
    path). ``attn`` supplies head geometry; rope=False for learned-
    position models; ``proj`` overrides the output projection
    (defaults to attn.o_proj).

    ``tables`` is ``(block_tables, gate)``. The gate is per-slot
    validity: a boolean active mask (decode convention) or an int32
    VALID count (tokens of the chunk that are real) — both normalize to
    counts, and a single UNIFIED ragged path serves every shape: each
    slot's k/v tokens are written into its pages at ``ctx .. ctx +
    valid - 1`` (padding and inactive slots routed to the reserved
    trash page) and its queries attend causally over the paged history
    through ``ops.paged_attention.ragged_paged_attention`` — one
    attention entry point whether the slot carries a prefill chunk
    (valid > 1), a decode step (valid == 1) or is idle (valid == 0),
    so mixed batches compile ONE program.

    Quantized KV (ISSUE 20): a 4-tuple ``cache`` — ``(k_pages,
    v_pages, k_scales, v_scales)`` with int8/fp8 data pools and f32
    page-parallel scales pools — routes through the quantize-at-write
    / dequant-in-kernel pair instead; the quant mode rides the pool
    dtype, so this compiles the same single program shape per mode."""
    b, s = q.shape[0], q.shape[1]
    tbl, gate = tables
    if rope:
        q = rope_with_offset(q, pos, attn.cfg.max_position_embeddings,
                             attn.cfg.rope_theta)
        k = rope_with_offset(k, pos, attn.cfg.max_position_embeddings,
                             attn.cfg.rope_theta)

    if len(cache) == 4:
        def fnq(qa, ka, va, kpa, vpa, ksa, vsa, tba, gatea, cta):
            from ..ops import paged_attention as PA
            ct = cta[:, 0]
            valid = gatea.astype(jnp.int32)
            kpa, vpa, ksa, vsa = PA.paged_prefill_write_quant(
                kpa, vpa, ksa, vsa, ka, va, tba, ct, valid)
            out = PA.ragged_paged_attention(qa, kpa, vpa, tba, ct,
                                            valid, k_scales=ksa,
                                            v_scales=vsa)
            return out, kpa, vpa, ksa, vsa

        ctx_out, kp2, vp2, ks2, vs2 = apply(
            fnq, q, k, v, cache[0], cache[1], cache[2], cache[3], tbl,
            gate, pos, n_outputs=5, name="paged_decode_attention_quant",
            differentiable=False)
        new_cache = (kp2, vp2, ks2, vs2)
    else:
        def fn(qa, ka, va, kpa, vpa, tba, gatea, cta):
            from ..ops import paged_attention as PA
            ct = cta[:, 0]
            valid = gatea.astype(jnp.int32)
            kpa, vpa = PA.paged_prefill_write(kpa, vpa, ka, va, tba,
                                              ct, valid)
            out = PA.ragged_paged_attention(qa, kpa, vpa, tba, ct,
                                            valid)
            return out, kpa, vpa

        ctx_out, kp2, vp2 = apply(
            fn, q, k, v, cache[0], cache[1], tbl, gate, pos,
            n_outputs=3, name="paged_decode_attention",
            differentiable=False)
        new_cache = (kp2, vp2)
    ctx_out = M.reshape(ctx_out, [b, s, attn.num_heads * attn.head_dim])
    out_proj = proj if proj is not None else attn.o_proj
    return out_proj(ctx_out), new_cache


def _alloc_kv_caches(cfg, batch_size, max_length, dtype):
    """Zero KV caches: per layer (k, v) of [B, max_len, KV, D]."""
    caches = []
    for _ in range(cfg.num_hidden_layers):
        for _kv in range(2):
            caches.append(creation.zeros(
                [batch_size, max_length, cfg.num_key_value_heads,
                 cfg.head_dim], dtype=dtype))
    return caches


def _lin(cfg, in_f, out_f, *, column, gather_output=False,
         input_is_parallel=True):
    init = nn.initializer.Normal(0.0, cfg.initializer_range)
    attr = nn.ParamAttr(initializer=init)
    if cfg.tensor_parallel:
        if column:
            return ColumnParallelLinear(in_f, out_f, weight_attr=attr,
                                        has_bias=False,
                                        gather_output=gather_output)
        return RowParallelLinear(in_f, out_f, weight_attr=attr,
                                 has_bias=False,
                                 input_is_parallel=input_is_parallel)
    return nn.Linear(in_f, out_f, weight_attr=attr, bias_attr=False)


class LlamaAttention(nn.Layer):
    def __init__(self, cfg: LlamaConfig):
        super().__init__()
        self.cfg = cfg
        self.num_heads = cfg.num_attention_heads
        self.num_kv_heads = cfg.num_key_value_heads
        self.head_dim = cfg.head_dim
        self.q_proj = _lin(cfg, cfg.hidden_size,
                           self.num_heads * self.head_dim, column=True)
        self.k_proj = _lin(cfg, cfg.hidden_size,
                           self.num_kv_heads * self.head_dim, column=True)
        self.v_proj = _lin(cfg, cfg.hidden_size,
                           self.num_kv_heads * self.head_dim, column=True)
        self.o_proj = _lin(cfg, self.num_heads * self.head_dim,
                           cfg.hidden_size, column=False)

    def forward(self, x, sin_cos=None, cache=None, pos=None, tables=None):
        b, s, _ = x.shape
        q = M.reshape(self.q_proj(x), [b, s, self.num_heads, self.head_dim])
        k = M.reshape(self.k_proj(x),
                      [b, s, self.num_kv_heads, self.head_dim])
        v = M.reshape(self.v_proj(x),
                      [b, s, self.num_kv_heads, self.head_dim])
        if cache is not None and tables is not None:
            return _paged_attention_step(self, q, k, v, cache, pos,
                                         tables)
        if cache is not None:
            q = rope_with_offset(q, pos, self.cfg.max_position_embeddings,
                                 self.cfg.rope_theta)
            k = rope_with_offset(k, pos, self.cfg.max_position_embeddings,
                                 self.cfg.rope_theta)
            ctx, k_cache, v_cache = F.sdpa_with_cache(
                q, k, v, cache[0], cache[1], pos)
            ctx = M.reshape(ctx, [b, s, self.num_heads * self.head_dim])
            return self.o_proj(ctx), (k_cache, v_cache)
        from ..distributed.fleet.meta_parallel.context_parallel import (
            sep_attention, sep_attention_manual, sep_axis_is_manual)
        sep_manual = (self.cfg.sep_parallel is not None
                      and sep_axis_is_manual())
        if not sep_manual:
            from ..incubate.nn.functional import \
                fused_rotary_position_embedding
            q, k, _ = fused_rotary_position_embedding(
                q, k, None, rotary_emb_base=self.cfg.rope_theta)
        if sep_manual:
            # 5D hybrid: inside the compiled pipeline's manual region
            # the sequence is physically local — rope needs global
            # positions, applied inside the wrapper from the bound
            # 'sep' axis index
            ctx = sep_attention_manual(
                q, k, v, rope_theta=self.cfg.rope_theta,
                causal=True, impl=self.cfg.sep_parallel)
        elif self.cfg.sep_parallel is not None:
            ctx = sep_attention(q, k, v, causal=True,
                                impl=self.cfg.sep_parallel)
        else:
            ctx = F.scaled_dot_product_attention(q, k, v, is_causal=True)
        ctx = M.reshape(ctx, [b, s, self.num_heads * self.head_dim])
        return self.o_proj(ctx)


class LlamaMLP(nn.Layer):
    def __init__(self, cfg: LlamaConfig):
        super().__init__()
        self.gate_proj = _lin(cfg, cfg.hidden_size, cfg.intermediate_size,
                              column=True)
        self.up_proj = _lin(cfg, cfg.hidden_size, cfg.intermediate_size,
                            column=True)
        self.down_proj = _lin(cfg, cfg.intermediate_size, cfg.hidden_size,
                              column=False)

    def forward(self, x):
        return self.down_proj(F.swiglu(self.gate_proj(x), self.up_proj(x)))


class LlamaDecoderLayer(nn.Layer):
    def __init__(self, cfg: LlamaConfig):
        super().__init__()
        self.cfg = cfg
        self.input_layernorm = nn.RMSNorm(cfg.hidden_size, cfg.rms_norm_eps)
        self.self_attn = LlamaAttention(cfg)
        self.post_attention_layernorm = nn.RMSNorm(cfg.hidden_size,
                                                   cfg.rms_norm_eps)
        self.mlp = LlamaMLP(cfg)
        if cfg.sequence_parallel:
            from ..distributed.fleet.utils import \
                mark_as_sequence_parallel_parameter
            for p in self.input_layernorm.parameters():
                mark_as_sequence_parallel_parameter(p)
            for p in self.post_attention_layernorm.parameters():
                mark_as_sequence_parallel_parameter(p)

    def _sp(self, t):
        if not self.cfg.sequence_parallel:
            return t
        from ..distributed.fleet.utils import ScatterOp
        return ScatterOp(t, axis=1)

    def forward(self, x, cache=None, pos=None, tables=None):
        if cache is not None:
            attn, new_cache = self.self_attn(self.input_layernorm(x),
                                             cache=cache, pos=pos,
                                             tables=tables)
            x = x + attn
            x = x + self.mlp(self.post_attention_layernorm(x))
            return x, new_cache
        if self.cfg.sequence_parallel or self.cfg.sep_parallel is not None:
            x = x + self.self_attn(self._sp(self.input_layernorm(x)))
            x = x + self.mlp(self._sp(self.post_attention_layernorm(x)))
            return x
        # plain path: composed from the SAME stages core_attn remat uses,
        # so there is exactly one copy of the qkv/rope/residual wiring
        q, k, v = self._qkv_stage(x)
        ctx = F.scaled_dot_product_attention(q, k, v, is_causal=True)
        return self._post_stage(x, ctx)

    # ---- core_attn selective remat (see LlamaConfig.recompute_granularity)
    def _qkv_from(self, h):
        """q/k/v projections + rope from an already-normed input —
        the single copy of the projection wiring, shared by the plain,
        core_attn-remat and fused-residual paths."""
        a = self.self_attn
        b, s, _ = h.shape
        q = M.reshape(a.q_proj(h), [b, s, a.num_heads, a.head_dim])
        k = M.reshape(a.k_proj(h), [b, s, a.num_kv_heads, a.head_dim])
        v = M.reshape(a.v_proj(h), [b, s, a.num_kv_heads, a.head_dim])
        from ..incubate.nn.functional import \
            fused_rotary_position_embedding
        q, k, _ = fused_rotary_position_embedding(
            q, k, None, rotary_emb_base=a.cfg.rope_theta)
        return q, k, v

    def _qkv_stage(self, x):
        return self._qkv_from(self.input_layernorm(x))

    def _post_stage(self, x, ctx):
        a = self.self_attn
        b, s, _ = x.shape
        ctx = M.reshape(ctx, [b, s, a.num_heads * a.head_dim])
        x = x + a.o_proj(ctx)
        x = x + self.mlp(self.post_attention_layernorm(x))
        return x

    def forward_core_attn_remat(self, x):
        """Remat the projections/norms/MLP but keep the flash-attention
        core OUTSIDE the checkpoint region: its output is a saved
        residual, so backward never re-runs the attention forward (the
        custom-vjp kernel is opaque to the dots_saveable policy)."""
        from ..incubate.recompute import recompute
        a = self.self_attn
        q, k, v = recompute(
            self._qkv_stage, x, n_outputs=3,
            params_from=[self.input_layernorm, a.q_proj, a.k_proj,
                         a.v_proj])
        ctx = F.scaled_dot_product_attention(q, k, v, is_causal=True)
        return recompute(
            self._post_stage, x, ctx,
            params_from=[a.o_proj, self.post_attention_layernorm,
                         self.mlp])

    # ---- fused residual+norm carry (FLAGS_fused_rmsnorm_residual) --------
    # The unfused stack computes ``x1 = x + attn(norm1(x)); x2 = x1 +
    # mlp(norm2(x1))`` — each residual add is immediately followed by
    # an RMSNorm (the next layer's norm1 for the mlp add). The fused
    # path therefore carries the UN-ADDED pair (hidden, residual)
    # between layers so every add+norm pair lowers into ONE fused
    # kernel (ops/pallas/rms_norm.rms_norm_residual on TPU): layer i's
    # mlp output + residual stream fuse into layer i+1's input_layernorm
    # and the attention output + residual fuse into
    # post_attention_layernorm; LlamaModel fuses the final add into the
    # last norm. Addition commutes, so the carry is numerics-identical
    # to the sequential adds.

    def _norm_pair(self, norm, hidden, residual):
        """(normed, summed) for the add+norm pair; a None residual
        (stack entry) degrades to the plain norm with the hidden
        itself as the stream."""
        if residual is None:
            return norm(hidden), hidden
        return F.fused_rms_norm_residual(hidden, residual, norm.weight,
                                         norm.epsilon)

    def forward_fused(self, hidden, residual=None):
        """One decoder layer over the (hidden, residual) carry; returns
        the next un-added pair ``(mlp_out, attn_residual_stream)``."""
        y1, r = self._norm_pair(self.input_layernorm, hidden, residual)
        q, k, v = self._qkv_from(y1)
        ctx = F.scaled_dot_product_attention(q, k, v, is_causal=True)
        return self._post_stage_fused(ctx, r)

    def _qkv_stage_fused(self, hidden, residual=None):
        y1, r = self._norm_pair(self.input_layernorm, hidden, residual)
        q, k, v = self._qkv_from(y1)
        return q, k, v, r

    def _post_stage_fused(self, ctx, r):
        a = self.self_attn
        b, s, _ = r.shape
        ctx = M.reshape(ctx, [b, s, a.num_heads * a.head_dim])
        y2, r2 = self._norm_pair(self.post_attention_layernorm,
                                 a.o_proj(ctx), r)
        return self.mlp(y2), r2

    def forward_fused_core_attn_remat(self, hidden, residual):
        """core_attn selective remat over the fused carry: same
        checkpoint regions as :meth:`forward_core_attn_remat`, with the
        fused residual+norm kernels INSIDE them — backward recompute
        re-runs the fused kernels, not an unfused expansion."""
        from ..incubate.recompute import recompute
        a = self.self_attn
        qkv_params = [self.input_layernorm, a.q_proj, a.k_proj, a.v_proj]
        if residual is None:
            q, k, v, r = recompute(self._qkv_stage_fused, hidden,
                                   n_outputs=4, params_from=qkv_params)
        else:
            q, k, v, r = recompute(self._qkv_stage_fused, hidden,
                                   residual, n_outputs=4,
                                   params_from=qkv_params)
        ctx = F.scaled_dot_product_attention(q, k, v, is_causal=True)
        return recompute(
            self._post_stage_fused, ctx, r, n_outputs=2,
            params_from=[a.o_proj, self.post_attention_layernorm,
                         self.mlp])


class LlamaModel(nn.Layer):
    def __init__(self, config: LlamaConfig):
        super().__init__()
        self.config = config
        init = nn.initializer.Normal(0.0, config.initializer_range)
        if config.tensor_parallel:
            self.embed_tokens = VocabParallelEmbedding(
                config.vocab_size, config.hidden_size,
                weight_attr=nn.ParamAttr(initializer=init))
        else:
            self.embed_tokens = nn.Embedding(
                config.vocab_size, config.hidden_size,
                weight_attr=nn.ParamAttr(initializer=init))
        self.layers = nn.LayerList([LlamaDecoderLayer(config)
                                    for _ in range(config.num_hidden_layers)])
        self.norm = nn.RMSNorm(config.hidden_size, config.rms_norm_eps)

    def forward(self, input_ids, caches=None, pos=None, tables=None,
                skip_layers=None):
        x = self.embed_tokens(input_ids)
        if caches is not None:
            # skip_layers (speculative decoding, ISSUE 18): the listed
            # decoder layers are passed through entirely — hidden state
            # AND their KV caches flow unchanged — giving a cheap
            # self-speculative draft model over the same weights
            # (LayerSkip-style early exit). Serving-path only.
            skip = frozenset(skip_layers) if skip_layers else frozenset()
            new_caches = []
            # 2 pools per layer (k, v), or 4 under quantized KV
            # (k, v, k_scales, v_scales) — ISSUE 20
            stride = len(caches) // len(self.layers)
            for i, layer in enumerate(self.layers):
                lc = tuple(caches[stride * i:stride * (i + 1)])
                if i in skip:
                    new_caches.extend(lc)
                    continue
                x, kv = layer(x, cache=lc, pos=pos, tables=tables)
                new_caches.extend(kv)
            return self.norm(x), new_caches
        if skip_layers:
            raise ValueError("skip_layers requires the caches "
                             "(serving) path")
        from ..nn.scan import scan_layers, can_scan
        if getattr(self.config, "scan_layers", True) and \
                can_scan(self.layers):
            if (getattr(self.config, "recompute_granularity", "full")
                    != "full"
                    and self.config.use_recompute
                    and self.training):
                import warnings
                warnings.warn(
                    "recompute_granularity is ignored under "
                    "scan_layers=True (the scan body remats whole "
                    "layers); set scan_layers=False for selective remat",
                    stacklevel=2)
            # one lax.scan over stacked per-layer weights: code size (the
            # measured TPU bottleneck for unrolled stacks) stays that of
            # a single layer; remat folds in as checkpointed scan body,
            # and the remat DOSE (full_save_interval) as fs-layer scan
            # groups whose last layer saves whole (nn/scan.py)
            x = scan_layers(self.layers, x,
                            remat=self.config.use_recompute
                            and self.training,
                            full_save_interval=getattr(
                                self.config, "full_save_interval", 0))
        else:
            gran = getattr(self.config, "recompute_granularity", "full")
            if gran not in ("full", "core_attn", "full_attn"):
                raise ValueError(
                    f"recompute_granularity={gran!r} is not one of "
                    "'full' | 'core_attn' | 'full_attn'")
            # PaddleNLP's 'full_attn' (save the attention, recompute the
            # rest) maps to the same TPU structure as core_attn
            selective = (
                gran in ("core_attn", "full_attn")
                and self.config.sep_parallel is None
                and not self.config.sequence_parallel)
            interval = max(
                int(getattr(self.config, "core_attn_interval", 1)), 1)
            # remat DOSE: every k-th layer keeps its activations whole
            # (no recompute at all) — spends leftover HBM to cut the
            # backward's re-forward time. 0 = off.
            fs = max(int(getattr(self.config, "full_save_interval", 0)),
                     0)
            # fused residual+norm carry (LlamaDecoderLayer.forward_fused
            # block comment): every add+norm pair — including the final
            # norm — lowers into one fused kernel. Only on the unrolled
            # stack (the on-chip bench path); the scan body keeps the
            # single-tensor carry.
            fused = (flags.flag("FLAGS_fused_rmsnorm_residual")
                     and self.config.sep_parallel is None
                     and not self.config.sequence_parallel)
            if fused:
                hidden, residual = x, None
                from ..incubate.recompute import recompute
                for i, layer in enumerate(self.layers):
                    if self.config.use_recompute and self.training:
                        if fs and i % fs == fs - 1:
                            hidden, residual = layer.forward_fused(
                                hidden, residual)
                        elif selective and i % interval == 0:
                            hidden, residual = \
                                layer.forward_fused_core_attn_remat(
                                    hidden, residual)
                        elif residual is None:
                            hidden, residual = recompute(
                                layer.forward_fused, hidden,
                                n_outputs=2, params_from=layer)
                        else:
                            hidden, residual = recompute(
                                layer.forward_fused, hidden, residual,
                                n_outputs=2, params_from=layer)
                    else:
                        hidden, residual = layer.forward_fused(
                            hidden, residual)
                if residual is None:
                    return self.norm(hidden)
                y, _ = F.fused_rms_norm_residual(
                    hidden, residual, self.norm.weight,
                    self.norm.epsilon)
                return y
            for i, layer in enumerate(self.layers):
                if self.config.use_recompute and self.training:
                    if fs and i % fs == fs - 1:
                        x = layer(x)
                    elif selective and i % interval == 0:
                        x = layer.forward_core_attn_remat(x)
                    else:
                        from ..incubate.recompute import recompute
                        x = recompute(layer, x)
                else:
                    x = layer(x)
        return self.norm(x)


class LlamaForCausalLM(nn.Layer, GenerationMixin):
    def __init__(self, config: LlamaConfig):
        super().__init__()
        self.llama = LlamaModel(config)
        self.config = config
        if not config.tie_word_embeddings:
            self.lm_head = _lin(config, config.hidden_size,
                                config.vocab_size, column=True,
                                gather_output=True)
        else:
            self.lm_head = None

    def init_kv_cache(self, batch_size, max_length, dtype=None):
        if dtype is None:
            dtype = next(iter(self.parameters())).dtype
        return _alloc_kv_caches(self.config, batch_size, max_length, dtype)

    def forward(self, input_ids, labels=None, caches=None, pos=None,
                tables=None, skip_layers=None):
        if caches is not None:
            hidden, caches = self.llama(input_ids, caches=caches, pos=pos,
                                        tables=tables,
                                        skip_layers=skip_layers)
        else:
            hidden = self.llama(input_ids)
        if labels is not None and caches is None and \
                self.lm_head is not None and \
                flags.flag("FLAGS_fused_linear_cross_entropy") and \
                not in_traced_collective():
            # chunked fused lm_head+CE: never materializes [N, V] logits
            # (~0.8GB of HBM traffic at N=4k, V=32k). Logits are not
            # computed on this path — the labeled training forward
            # returns (None, loss).
            from ..ops.fused_ce import fused_linear_cross_entropy as flce
            h2 = M.reshape(hidden[:, :-1, :],
                           [-1, self.config.hidden_size])
            l2 = M.reshape(labels[:, 1:], [-1])
            loss = apply(flce, h2, self.lm_head.weight, l2,
                         name="fused_linear_xent")
            return None, loss
        if self.lm_head is not None:
            logits = self.lm_head(hidden)
        else:
            logits = matmul(hidden, self.llama.embed_tokens.weight,
                            transpose_y=True)
        if caches is not None:
            return logits, caches
        if labels is None:
            return logits
        shift_logits = logits[:, :-1, :]
        shift_labels = labels[:, 1:]
        loss = F.cross_entropy(
            M.reshape(shift_logits, [-1, self.config.vocab_size]),
            M.reshape(shift_labels, [-1]))
        return logits, loss


# ---------------------------------------------------------------------------
# Pipeline-parallel Llama (the reference's PaddleNLP LlamaForCausalLMPipe
# shape — BASELINE config 4's 4D hybrid workload). The decoder stack is the
# uniform pipeline body: PipelineParallel stacks the per-layer weights
# [S, ...] over the 'pipe' mesh axis while each layer's TP layers keep their
# 'model'-axis sharding and the optimizer state stays ZeRO-sharded over
# 'sharding' — one compiled program, all four axes live.
# ---------------------------------------------------------------------------


class LlamaEmbeddingPipe(nn.Layer):
    """Pipeline prologue: token embedding (vocab-parallel under TP)."""

    def __init__(self, cfg: LlamaConfig):
        super().__init__()
        init = nn.initializer.Normal(0.0, cfg.initializer_range)
        if cfg.tensor_parallel:
            self.embed_tokens = VocabParallelEmbedding(
                cfg.vocab_size, cfg.hidden_size,
                weight_attr=nn.ParamAttr(initializer=init))
        else:
            self.embed_tokens = nn.Embedding(
                cfg.vocab_size, cfg.hidden_size,
                weight_attr=nn.ParamAttr(initializer=init))

    def forward(self, input_ids):
        return self.embed_tokens(input_ids)


class LlamaHeadPipe(nn.Layer):
    """Pipeline epilogue: final RMSNorm + LM head -> logits."""

    def __init__(self, cfg: LlamaConfig):
        super().__init__()
        self.norm = nn.RMSNorm(cfg.hidden_size, cfg.rms_norm_eps)
        self.lm_head = _lin(cfg, cfg.hidden_size, cfg.vocab_size,
                            column=True, gather_output=True)

    def forward(self, hidden):
        return self.lm_head(self.norm(hidden))


class LlamaPretrainingCriterion(nn.Layer):
    """Shifted next-token cross entropy — identical numerics to
    ``LlamaForCausalLM``'s labeled forward, so pipelined training is
    loss-parity-comparable against the monolithic model.

    ``fuses_with_network_loss`` certifies exactly that contract to
    ``hapi.Model``: ``network(x, labels=y)[1]`` equals
    ``criterion(network(x), y)``, so the compiled fit step may route
    labels into the network and let the fused linear+cross-entropy
    path (FLAGS_fused_linear_cross_entropy) skip the [N, V] logits."""

    fuses_with_network_loss = True

    def __init__(self, cfg: LlamaConfig):
        super().__init__()
        self.vocab_size = cfg.vocab_size

    def forward(self, logits, labels):
        shift_logits = logits[:, :-1, :]
        shift_labels = labels[:, 1:]
        return F.cross_entropy(
            M.reshape(shift_logits, [-1, self.vocab_size]),
            M.reshape(shift_labels, [-1]))


def LlamaForCausalLMPipe(config: LlamaConfig, **pipeline_kwargs):
    """Build the pipelined Llama as a ``PipelineLayer``.

    Layer construction order (embedding, decoder stack, norm+head) matches
    ``LlamaForCausalLM`` exactly, so with the same seed both models draw
    identical initial weights — the basis of every parity test. Pass
    ``num_virtual_pipeline_stages`` etc. through ``pipeline_kwargs``."""
    from ..distributed.fleet.meta_parallel import LayerDesc, PipelineLayer

    descs = [LayerDesc(LlamaEmbeddingPipe, config)] + \
        [LayerDesc(LlamaDecoderLayer, config)
         for _ in range(config.num_hidden_layers)] + \
        [LayerDesc(LlamaHeadPipe, config)]
    pipeline_kwargs.setdefault("loss_fn", LlamaPretrainingCriterion(config))
    pipeline_kwargs.setdefault(
        "recompute_interval", 1 if config.use_recompute else 0)
    return PipelineLayer(descs, **pipeline_kwargs)
