"""DeepSeek-V2-style model family: Multi-head Latent Attention (MLA) +
fine-grained MoE with shared experts (BASELINE config 5 names
DeepSeekMoE; reference workloads live in PaddleNLP — mount empty, see
SURVEY.md provenance warning).

TPU-native design notes:
- MLA compresses the KV stream into a small latent (``kv_lora_rank``)
  plus a shared decoupled-RoPE key (``qk_rope_head_dim``); the decode
  cache stores ONLY those two — the memory win that defines MLA. The
  up-projection back to per-head keys/values is a dense matmul (MXU
  food), recomputed per step from the latent.
- Attention q/k head dim (nope+rope) differs from the v head dim, which
  the flash kernel does not support — the MLA core runs as an einsum
  attention with fp32 softmax (XLA fuses the chain); the MoE FFN and all
  projections dominate FLOPs at DeepSeek shapes anyway.
- Routed experts reuse the framework ``MoELayer`` (grouped matmuls,
  ragged all-to-all over the 'expert' mesh axis when fleet EP is
  active); shared experts are a plain SwiGLU MLP added unconditionally
  (the DeepSeek-V2 formulation).
"""

from __future__ import annotations

from dataclasses import dataclass

import jax
import jax.numpy as jnp

from .. import nn
from ..framework.core import Tensor, apply
from ..nn import functional as F
from ..ops import manipulation as M
from ..ops.linalg import matmul
from ..distributed.parallel_layers import (ColumnParallelLinear,
                                           RowParallelLinear,
                                           VocabParallelEmbedding)
from ..incubate.distributed.models.moe import MoELayer
from ..generation import GenerationMixin
from .llama import rope_with_offset

__all__ = ["DeepseekV2Config", "DeepseekV2ForCausalLM"]


@dataclass
class DeepseekV2Config:
    vocab_size: int = 102400
    hidden_size: int = 5120
    num_hidden_layers: int = 60
    num_attention_heads: int = 128
    # MLA geometry
    q_lora_rank: int | None = 1536
    kv_lora_rank: int = 512
    qk_nope_head_dim: int = 128
    qk_rope_head_dim: int = 64
    v_head_dim: int = 128
    # FFN / MoE geometry
    intermediate_size: int = 12288       # dense layers
    moe_intermediate_size: int = 1536    # per routed expert
    n_routed_experts: int = 160
    n_shared_experts: int = 2
    num_experts_per_tok: int = 6
    first_k_dense_replace: int = 1       # leading dense layers
    routed_scaling_factor: float = 16.0
    norm_topk_prob: bool = False
    router_aux_loss_coef: float = 0.001
    #: MegaBlocks-style dropless dispatch (see Qwen2MoeConfig)
    moe_dropless: bool = False
    # common
    max_position_embeddings: int = 4096
    rope_theta: float = 10000.0
    rms_norm_eps: float = 1e-6
    initializer_range: float = 0.02
    tie_word_embeddings: bool = False
    use_recompute: bool = False
    tensor_parallel: bool = False

    @classmethod
    def tiny(cls):
        return cls(vocab_size=256, hidden_size=64, num_hidden_layers=3,
                   num_attention_heads=4, q_lora_rank=32,
                   kv_lora_rank=16, qk_nope_head_dim=16,
                   qk_rope_head_dim=8, v_head_dim=16,
                   intermediate_size=128, moe_intermediate_size=32,
                   n_routed_experts=8, n_shared_experts=1,
                   num_experts_per_tok=2, first_k_dense_replace=1,
                   routed_scaling_factor=1.0, norm_topk_prob=True,
                   max_position_embeddings=64)

    @property
    def qk_head_dim(self):
        return self.qk_nope_head_dim + self.qk_rope_head_dim


def _lin(cfg, in_f, out_f, *, column, gather_output=False):
    init = nn.initializer.Normal(0.0, cfg.initializer_range)
    attr = nn.ParamAttr(initializer=init)
    if cfg.tensor_parallel:
        if column:
            return ColumnParallelLinear(in_f, out_f, weight_attr=attr,
                                        has_bias=False,
                                        gather_output=gather_output)
        return RowParallelLinear(in_f, out_f, weight_attr=attr,
                                 has_bias=False)
    return nn.Linear(in_f, out_f, weight_attr=attr, bias_attr=False)


#: KV-chunk size of the blockwise MLA path; the exact einsum is kept
#: below 2 chunks of sequence where its one-shot matmul is cheaper.
_MLA_CHUNK = 256


def _mla_core(q, k, v, causal_offset=None, valid_len=None):
    """MLA attention. q/k: [B, Sq, H, Dqk], v: [B, Sk, H, Dv] — the
    q/k vs v head-dim asymmetry breaks the flash kernel's contract, so
    this core is hand-rolled. ``causal_offset`` is the absolute
    position of q's first row (decode: pos; train: 0); ``valid_len``
    masks the padded cache tail (decode).

    Two regimes: short sequences (and the cached decode step) use the
    exact einsum with fp32 softmax; the TRAIN path at
    Sq >= 2*_MLA_CHUNK switches to ``ops.ring_attention.
    chunked_attention`` — blockwise online-softmax, O(Sq*chunk) score
    memory instead of the S x S logits matrix, which is what makes
    MLA's latent-cache memory win real at long context."""
    dqk = q.shape[-1]

    if causal_offset is None and q.shape[1] >= 2 * _MLA_CHUNK:
        from ..ops.ring_attention import chunked_attention

        def fn_chunked(qq, kk, vv):
            return chunked_attention(qq, kk, vv, causal=True,
                                     chunk=_MLA_CHUNK)

        return apply(fn_chunked, q, k, v, name="mla_attention_chunked")

    def fn(qq, kk, vv, *rest):
        import math
        logits = jnp.einsum("bqhd,bkhd->bhqk", qq, kk,
                            preferred_element_type=jnp.float32)
        logits = logits / math.sqrt(dqk)
        sq, sk = qq.shape[1], kk.shape[1]
        qpos = jnp.arange(sq)
        if rest:                              # decode: absolute offset
            qpos = qpos + rest[0].astype(jnp.int32)
        kpos = jnp.arange(sk)
        mask = kpos[None, :] <= qpos[:, None]  # causal
        if len(rest) > 1:                      # cache validity
            mask = mask & (kpos[None, :] < rest[1].astype(jnp.int32))
        logits = jnp.where(mask[None, None], logits, -1e30)
        p = jax.nn.softmax(logits, axis=-1).astype(vv.dtype)
        return jnp.einsum("bhqk,bkhd->bqhd", p, vv)

    args = [q, k, v]
    if causal_offset is not None:
        args.append(causal_offset)
        if valid_len is not None:
            args.append(valid_len)
    return apply(fn, *args, name="mla_attention")


class DeepseekV2Attention(nn.Layer):
    """MLA: latent-compressed KV + decoupled RoPE key."""

    def __init__(self, cfg: DeepseekV2Config):
        super().__init__()
        self.cfg = cfg
        h, qk, rope = cfg.num_attention_heads, cfg.qk_head_dim, \
            cfg.qk_rope_head_dim
        if cfg.q_lora_rank:
            self.q_a_proj = _lin(cfg, cfg.hidden_size, cfg.q_lora_rank,
                                 column=False)
            self.q_a_layernorm = nn.RMSNorm(cfg.q_lora_rank,
                                            cfg.rms_norm_eps)
            self.q_b_proj = _lin(cfg, cfg.q_lora_rank, h * qk,
                                 column=True)
        else:
            self.q_proj = _lin(cfg, cfg.hidden_size, h * qk, column=True)
        # latent + decoupled rope key (shared across heads) in one proj
        self.kv_a_proj_with_mqa = _lin(
            cfg, cfg.hidden_size, cfg.kv_lora_rank + rope, column=False)
        self.kv_a_layernorm = nn.RMSNorm(cfg.kv_lora_rank,
                                         cfg.rms_norm_eps)
        self.kv_b_proj = _lin(
            cfg, cfg.kv_lora_rank,
            h * (cfg.qk_nope_head_dim + cfg.v_head_dim), column=True)
        self.o_proj = _lin(cfg, h * cfg.v_head_dim, cfg.hidden_size,
                           column=False)

    def _q(self, x, b, s, pos=None):
        cfg = self.cfg
        if cfg.q_lora_rank:
            q = self.q_b_proj(self.q_a_layernorm(self.q_a_proj(x)))
        else:
            q = self.q_proj(x)
        q = M.reshape(q, [b, s, cfg.num_attention_heads, cfg.qk_head_dim])
        q_nope = q[:, :, :, :cfg.qk_nope_head_dim]
        q_pe = q[:, :, :, cfg.qk_nope_head_dim:]
        zero = Tensor(jnp.zeros((b, 1), jnp.int32))
        q_pe = rope_with_offset(q_pe, pos if pos is not None else zero,
                                cfg.max_position_embeddings,
                                cfg.rope_theta)
        return M.concat([q_nope, q_pe], axis=-1)

    def _latent(self, x, b, s, pos=None):
        """(normed latent [B,S,R], rotated shared key [B,S,1,rope])."""
        cfg = self.cfg
        ckv = self.kv_a_proj_with_mqa(x)
        latent = self.kv_a_layernorm(ckv[:, :, :cfg.kv_lora_rank])
        k_pe = M.reshape(ckv[:, :, cfg.kv_lora_rank:],
                         [b, s, 1, cfg.qk_rope_head_dim])
        zero = Tensor(jnp.zeros((b, 1), jnp.int32))
        k_pe = rope_with_offset(k_pe, pos if pos is not None else zero,
                                cfg.max_position_embeddings,
                                cfg.rope_theta)
        return latent, k_pe

    def _expand_kv(self, latent, b, t):
        """Latent [B,T,R] -> per-head (k_nope [B,T,H,Dn], v [B,T,H,Dv])."""
        cfg = self.cfg
        kv = M.reshape(self.kv_b_proj(latent),
                       [b, t, cfg.num_attention_heads,
                        cfg.qk_nope_head_dim + cfg.v_head_dim])
        return (kv[:, :, :, :cfg.qk_nope_head_dim],
                kv[:, :, :, cfg.qk_nope_head_dim:])

    def forward(self, x, cache=None, pos=None):
        cfg = self.cfg
        b, s, _ = x.shape
        if cache is None:
            q = self._q(x, b, s)
            latent, k_pe = self._latent(x, b, s)
            k_nope, v = self._expand_kv(latent, b, s)
            k = M.concat(
                [k_nope, M.expand(k_pe, [b, s, cfg.num_attention_heads,
                                         cfg.qk_rope_head_dim])],
                axis=-1)
            ctx = _mla_core(q, k, v)
            ctx = M.reshape(ctx, [b, s,
                                  cfg.num_attention_heads * cfg.v_head_dim])
            return self.o_proj(ctx)

        # decode: cache = (latents [B,T,R], k_pe [B,T,1,rope]); the new
        # step's latent writes at ``pos``, attention runs over the whole
        # (masked) latent history re-expanded through kv_b — MLA's
        # cache is the latent, NOT per-head k/v
        lat_cache, pe_cache = cache
        q = self._q(x, b, s, pos=pos)
        latent, k_pe = self._latent(x, b, s, pos=pos)

        def write(buf, val, p):
            # pos arrives as a scalar from the decode loop (one shared
            # position) — normalize scalar/[B,1] alike
            start = jnp.reshape(p, (-1,))[0].astype(jnp.int32)
            return jax.lax.dynamic_update_slice_in_dim(
                buf, val.astype(buf.dtype), start, axis=1)
        lat_new = apply(lambda bf, vv, pp: write(bf, vv, pp),
                        lat_cache, latent, pos, name="mla_cache_write")
        pe_new = apply(lambda bf, vv, pp: write(bf, vv, pp),
                       pe_cache, k_pe, pos, name="mla_pe_write")
        t = lat_new.shape[1]
        k_nope, v = self._expand_kv(lat_new, b, t)
        k = M.concat(
            [k_nope, M.expand(pe_new, [b, t, cfg.num_attention_heads,
                                       cfg.qk_rope_head_dim])],
            axis=-1)
        valid = pos + s
        ctx = _mla_core(q, k, v, causal_offset=pos, valid_len=valid)
        ctx = M.reshape(ctx, [b, s,
                              cfg.num_attention_heads * cfg.v_head_dim])
        return self.o_proj(ctx), (lat_new, pe_new)


class DeepseekV2MLP(nn.Layer):
    def __init__(self, cfg, intermediate=None):
        super().__init__()
        inter = intermediate or cfg.intermediate_size
        self.gate_proj = _lin(cfg, cfg.hidden_size, inter, column=True)
        self.up_proj = _lin(cfg, cfg.hidden_size, inter, column=True)
        self.down_proj = _lin(cfg, inter, cfg.hidden_size, column=False)

    def forward(self, x):
        return self.down_proj(F.swiglu(self.gate_proj(x), self.up_proj(x)))


class DeepseekV2MoE(nn.Layer):
    """Fine-grained routed experts (scaled) + always-on shared experts."""

    def __init__(self, cfg: DeepseekV2Config):
        super().__init__()
        self.scaling = cfg.routed_scaling_factor
        self.moe = MoELayer(
            cfg.hidden_size, cfg.moe_intermediate_size,
            cfg.n_routed_experts,
            gate={"top_k": cfg.num_experts_per_tok,
                  "norm_topk_prob": cfg.norm_topk_prob,
                  "dropless": getattr(cfg, "moe_dropless", False)})
        self.shared_experts = DeepseekV2MLP(
            cfg, intermediate=cfg.moe_intermediate_size
            * cfg.n_shared_experts)

    def forward(self, x):
        return self.moe(x) * self.scaling + self.shared_experts(x)

    @property
    def aux_loss(self):
        return self.moe.aux_loss


class DeepseekV2DecoderLayer(nn.Layer):
    def __init__(self, cfg: DeepseekV2Config, layer_idx: int):
        super().__init__()
        self.input_layernorm = nn.RMSNorm(cfg.hidden_size,
                                          cfg.rms_norm_eps)
        self.self_attn = DeepseekV2Attention(cfg)
        self.post_attention_layernorm = nn.RMSNorm(cfg.hidden_size,
                                                   cfg.rms_norm_eps)
        self.is_moe = layer_idx >= cfg.first_k_dense_replace
        self.mlp = DeepseekV2MoE(cfg) if self.is_moe \
            else DeepseekV2MLP(cfg)

    def forward(self, x, cache=None, pos=None):
        if cache is not None:
            attn, new_cache = self.self_attn(self.input_layernorm(x),
                                             cache=cache, pos=pos)
            x = x + attn
            x = x + self.mlp(self.post_attention_layernorm(x))
            return x, new_cache
        attn = self.self_attn(self.input_layernorm(x))
        from ..framework import flags
        if flags.flag("FLAGS_fused_rmsnorm_residual"):
            # attention-residual add + post_attention_layernorm as ONE
            # fused kernel (models/llama.py fused-carry comment)
            y, r = F.fused_rms_norm_residual(
                attn, x, self.post_attention_layernorm.weight,
                self.post_attention_layernorm.epsilon)
            return r + self.mlp(y)
        x = x + attn
        x = x + self.mlp(self.post_attention_layernorm(x))
        return x


class DeepseekV2ForCausalLM(nn.Layer, GenerationMixin):
    def __init__(self, config: DeepseekV2Config):
        super().__init__()
        self.config = config
        init = nn.initializer.Normal(0.0, config.initializer_range)
        if config.tensor_parallel:
            self.embed_tokens = VocabParallelEmbedding(
                config.vocab_size, config.hidden_size,
                weight_attr=nn.ParamAttr(initializer=init))
        else:
            self.embed_tokens = nn.Embedding(
                config.vocab_size, config.hidden_size,
                weight_attr=nn.ParamAttr(initializer=init))
        self.layers = nn.LayerList(
            [DeepseekV2DecoderLayer(config, i)
             for i in range(config.num_hidden_layers)])
        self.norm = nn.RMSNorm(config.hidden_size, config.rms_norm_eps)
        self.lm_head = _lin(config, config.hidden_size,
                            config.vocab_size, column=True,
                            gather_output=True) \
            if not config.tie_word_embeddings else None

    def init_kv_cache(self, batch_size, max_length, dtype=None):
        """MLA cache: (latent [B,T,R], rope-key [B,T,1,rope]) per layer —
        R + rope floats per token instead of 2*H*D (the MLA win; e.g.
        576 vs 32768 at DeepSeek-V2 shapes)."""
        cfg = self.config
        if dtype is None:
            dtype = next(iter(self.parameters())).dtype
        caches = []
        for _ in range(cfg.num_hidden_layers):
            caches.append(Tensor(jnp.zeros(
                (batch_size, max_length, cfg.kv_lora_rank), dtype)))
            caches.append(Tensor(jnp.zeros(
                (batch_size, max_length, 1, cfg.qk_rope_head_dim),
                dtype)))
        return caches

    def forward(self, input_ids, labels=None, caches=None, pos=None):
        # no ``tables`` parameter: paged/continuous-batching serving is
        # not implemented for MLA yet — passing block tables must fail
        # loudly, not be silently ignored
        x = self.embed_tokens(input_ids)
        if caches is not None:
            new_caches = []
            for i, layer in enumerate(self.layers):
                x, (lc, pc) = layer(x, cache=(caches[2 * i],
                                              caches[2 * i + 1]),
                                    pos=pos)
                new_caches.extend((lc, pc))
            hidden = self.norm(x)
            logits = self.lm_head(hidden) if self.lm_head is not None \
                else matmul(hidden, self.embed_tokens.weight,
                            transpose_y=True)
            return logits, new_caches
        if self.training and self.config.use_recompute and \
                self.config.router_aux_loss_coef:
            # see qwen2.py: the per-layer aux attribute cannot cross the
            # jax.checkpoint boundary; fail clearly, not as a leaked
            # tracer (inference-only use of a training config is fine)
            raise ValueError(
                "router_aux_loss_coef > 0 with use_recompute=True is "
                "unsupported for training: set router_aux_loss_coef=0.0 "
                "or use_recompute=False.")
        for layer in self.layers:
            if self.config.use_recompute and self.training:
                from ..incubate.recompute import recompute
                x = recompute(layer, x)
            else:
                x = layer(x)
        hidden = self.norm(x)
        if self.lm_head is not None:
            logits = self.lm_head(hidden)
        else:
            logits = matmul(hidden, self.embed_tokens.weight,
                            transpose_y=True)
        if labels is None:
            return logits
        shift_logits = logits[:, :-1, :]
        shift_labels = labels[:, 1:]
        loss = F.cross_entropy(
            M.reshape(shift_logits, [-1, self.config.vocab_size]),
            M.reshape(shift_labels, [-1]))
        coef = self.config.router_aux_loss_coef
        if coef:
            # stored aux tracers cannot cross a jax.checkpoint boundary;
            # with coef=0 (recompute runs) the read is skipped entirely
            for layer in self.layers:
                if layer.is_moe and layer.mlp.aux_loss is not None:
                    loss = loss + coef * layer.mlp.aux_loss
        return logits, loss
