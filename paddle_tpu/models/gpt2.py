"""GPT-2 (BASELINE config 1: 124M LM, CPU-runnable reference model).

Written with the paddle-shaped Layer API; attention goes through
F.scaled_dot_product_attention (flash-attn kernel on TPU)."""

from __future__ import annotations

import math
from dataclasses import dataclass

import numpy as np

from ..framework.core import Tensor
from .. import nn
from ..nn import functional as F
from ..ops import creation, manipulation as M
from ..generation import GenerationMixin

__all__ = ["GPT2Config", "GPT2Model", "GPT2ForCausalLM"]


@dataclass
class GPT2Config:
    vocab_size: int = 50257
    hidden_size: int = 768
    num_hidden_layers: int = 12
    num_attention_heads: int = 12
    intermediate_size: int = 3072
    max_position_embeddings: int = 1024
    hidden_dropout_prob: float = 0.1
    attention_dropout_prob: float = 0.1
    layer_norm_epsilon: float = 1e-5
    initializer_range: float = 0.02
    # weight-only serving quantization switch — see LlamaConfig
    weight_quant: str | None = None

    @classmethod
    def small(cls):
        return cls()

    @classmethod
    def tiny(cls):
        return cls(vocab_size=512, hidden_size=64, num_hidden_layers=2,
                   num_attention_heads=4, intermediate_size=128,
                   max_position_embeddings=128, hidden_dropout_prob=0.0,
                   attention_dropout_prob=0.0)


class GPT2Attention(nn.Layer):
    def __init__(self, cfg: GPT2Config):
        super().__init__()
        self.num_heads = cfg.num_attention_heads
        self.head_dim = cfg.hidden_size // cfg.num_attention_heads
        init = nn.initializer.Normal(0.0, cfg.initializer_range)
        attr = nn.ParamAttr(initializer=init)
        self.c_attn = nn.Linear(cfg.hidden_size, 3 * cfg.hidden_size,
                                weight_attr=attr)
        self.c_proj = nn.Linear(cfg.hidden_size, cfg.hidden_size,
                                weight_attr=attr)
        self.attn_dropout = cfg.attention_dropout_prob

    def forward(self, x, cache=None, pos=None, tables=None):
        b, s, e = x.shape
        qkv = self.c_attn(x)
        qkv = M.reshape(qkv, [b, s, 3, self.num_heads, self.head_dim])
        q = qkv[:, :, 0]
        k = qkv[:, :, 1]
        v = qkv[:, :, 2]
        if cache is not None and tables is not None:
            from .llama import _paged_attention_step
            return _paged_attention_step(self, q, k, v, cache, pos,
                                         tables, rope=False,
                                         proj=self.c_proj)
        if cache is not None:
            ctx, k_cache, v_cache = F.sdpa_with_cache(
                q, k, v, cache[0], cache[1], pos)
            ctx = M.reshape(ctx, [b, s, e])
            return self.c_proj(ctx), (k_cache, v_cache)
        ctx = F.scaled_dot_product_attention(
            q, k, v, is_causal=True, dropout_p=self.attn_dropout,
            training=self.training)
        ctx = M.reshape(ctx, [b, s, e])
        return self.c_proj(ctx)


class GPT2MLP(nn.Layer):
    def __init__(self, cfg: GPT2Config):
        super().__init__()
        init = nn.initializer.Normal(0.0, cfg.initializer_range)
        attr = nn.ParamAttr(initializer=init)
        self.c_fc = nn.Linear(cfg.hidden_size, cfg.intermediate_size,
                              weight_attr=attr)
        self.c_proj = nn.Linear(cfg.intermediate_size, cfg.hidden_size,
                                weight_attr=attr)

    def forward(self, x):
        return self.c_proj(F.gelu(self.c_fc(x), approximate=True))


class GPT2Block(nn.Layer):
    def __init__(self, cfg: GPT2Config):
        super().__init__()
        self.ln_1 = nn.LayerNorm(cfg.hidden_size, cfg.layer_norm_epsilon)
        self.attn = GPT2Attention(cfg)
        self.ln_2 = nn.LayerNorm(cfg.hidden_size, cfg.layer_norm_epsilon)
        self.mlp = GPT2MLP(cfg)
        self.dropout = nn.Dropout(cfg.hidden_dropout_prob)

    def forward(self, x, cache=None, pos=None, tables=None):
        if cache is not None:
            attn, new_cache = self.attn(self.ln_1(x), cache=cache, pos=pos,
                                        tables=tables)
            x = x + attn
            x = x + self.mlp(self.ln_2(x))
            return x, new_cache
        x = x + self.dropout(self.attn(self.ln_1(x)))
        x = x + self.dropout(self.mlp(self.ln_2(x)))
        return x


class GPT2Model(nn.Layer):
    def __init__(self, config: GPT2Config):
        super().__init__()
        self.config = config
        init = nn.initializer.Normal(0.0, config.initializer_range)
        self.wte = nn.Embedding(config.vocab_size, config.hidden_size,
                                weight_attr=nn.ParamAttr(initializer=init))
        self.wpe = nn.Embedding(config.max_position_embeddings,
                                config.hidden_size,
                                weight_attr=nn.ParamAttr(initializer=init))
        self.drop = nn.Dropout(config.hidden_dropout_prob)
        self.h = nn.LayerList([GPT2Block(config)
                               for _ in range(config.num_hidden_layers)])
        self.ln_f = nn.LayerNorm(config.hidden_size,
                                 config.layer_norm_epsilon)

    def forward(self, input_ids, caches=None, pos=None, tables=None):
        s = input_ids.shape[1]
        positions = creation.arange(0, s, dtype="int64")
        if pos is not None:
            positions = positions + pos.astype("int64")
        x = self.wte(input_ids) + self.wpe(positions)
        if caches is not None:
            new_caches = []
            # 2 pools per layer, or 4 under quantized KV (ISSUE 20)
            stride = len(caches) // len(self.h)
            for i, block in enumerate(self.h):
                x, kv = block(
                    x, cache=tuple(caches[stride * i:stride * (i + 1)]),
                    pos=pos, tables=tables)
                new_caches.extend(kv)
            return self.ln_f(x), new_caches
        x = self.drop(x)
        from ..nn.scan import scan_layers, can_scan
        dropout_live = (self.training
                        and (self.config.hidden_dropout_prob > 0
                             or self.config.attention_dropout_prob > 0))
        if not dropout_live and can_scan(self.h):
            # per-layer RNG (live dropout) forces the unrolled path
            x = scan_layers(self.h, x)
        else:
            for block in self.h:
                x = block(x)
        return self.ln_f(x)


class GPT2ForCausalLM(nn.Layer, GenerationMixin):
    """LM head ties the embedding matrix (GPT-2 convention)."""

    def __init__(self, config: GPT2Config):
        super().__init__()
        self.gpt2 = GPT2Model(config)
        self.config = config

    def init_kv_cache(self, batch_size, max_length, dtype=None):
        cfg = self.config
        if dtype is None:
            dtype = next(iter(self.parameters())).dtype
        head_dim = cfg.hidden_size // cfg.num_attention_heads
        return [creation.zeros([batch_size, max_length,
                                cfg.num_attention_heads, head_dim],
                               dtype=dtype)
                for _ in range(2 * cfg.num_hidden_layers)]

    def forward(self, input_ids, labels=None, caches=None, pos=None,
                tables=None):
        from ..ops.linalg import matmul
        if caches is not None:
            hidden, caches = self.gpt2(input_ids, caches=caches, pos=pos,
                                       tables=tables)
            logits = matmul(hidden, self.gpt2.wte.weight, transpose_y=True)
            return logits, caches
        hidden = self.gpt2(input_ids)
        logits = matmul(hidden, self.gpt2.wte.weight, transpose_y=True)
        if labels is None:
            return logits
        # shift: predict token t+1 from prefix ≤ t
        shift_logits = logits[:, :-1, :]
        shift_labels = labels[:, 1:]
        loss = F.cross_entropy(
            M.reshape(shift_logits, [-1, self.config.vocab_size]),
            M.reshape(shift_labels, [-1]))
        return logits, loss
