"""Model zoo — the role PaddleNLP's ``llm/`` + ``paddlenlp/transformers``
plays for the reference (SURVEY.md §0: the baseline workloads are PaddleNLP
scripts driving the framework). TPU-first implementations built on
paddle_tpu's nn + parallel layers + Pallas kernels."""

from .gpt2 import GPT2Config, GPT2Model, GPT2ForCausalLM
from .llama import (LlamaConfig, LlamaModel, LlamaForCausalLM,
                    LlamaForCausalLMPipe, LlamaPretrainingCriterion)
from .qwen2 import (Qwen2Config, Qwen2MoeConfig, Qwen2ForCausalLM,
                    Qwen2MoeForCausalLM, Qwen2MoeForCausalLMPipe,
                    Qwen2MoePretrainingCriterion)
from .ernie import (ErnieConfig, ErnieModel, ErnieForPretraining,
                    ErnieForMaskedLM, ErnieForSequenceClassification)
from .deepseek import DeepseekV2Config, DeepseekV2ForCausalLM

__all__ = ["GPT2Config", "GPT2Model", "GPT2ForCausalLM", "LlamaConfig",
           "LlamaModel", "LlamaForCausalLM", "LlamaForCausalLMPipe",
           "LlamaPretrainingCriterion", "Qwen2Config",
           "Qwen2MoeConfig", "Qwen2ForCausalLM", "Qwen2MoeForCausalLM",
           "Qwen2MoeForCausalLMPipe", "Qwen2MoePretrainingCriterion",
           "ErnieConfig", "ErnieModel", "ErnieForPretraining",
           "ErnieForMaskedLM", "ErnieForSequenceClassification", "DeepseekV2Config", "DeepseekV2ForCausalLM"]
