"""ERNIE encoder family (BASELINE config 3: ERNIE-3.0-base pretrain DP).

Role of PaddleNLP's ``paddlenlp/transformers/ernie`` model family driving
the reference framework (SURVEY.md §0; reference mount empty, no file:line
cites). ERNIE is a BERT-shaped bidirectional encoder with an extra
*task-type* embedding; pretraining pairs masked-LM with a sentence-order
objective.

TPU-first: full-sequence bidirectional attention goes through
``F.scaled_dot_product_attention`` (Pallas flash-attention kernel on TPU);
everything is static-shape so XLA tiles the 12 encoder matmuls onto the
MXU back-to-back.
"""

from __future__ import annotations

from dataclasses import dataclass

from .. import nn
from ..nn import functional as F
from ..ops import creation, manipulation as M

__all__ = ["ErnieConfig", "ErnieModel", "ErnieForPretraining",
           "ErnieForSequenceClassification", "ErnieForMaskedLM"]


@dataclass
class ErnieConfig:
    vocab_size: int = 40000
    hidden_size: int = 768
    num_hidden_layers: int = 12
    num_attention_heads: int = 12
    intermediate_size: int = 3072
    max_position_embeddings: int = 2048
    type_vocab_size: int = 4
    task_type_vocab_size: int = 3
    use_task_id: bool = True
    hidden_dropout_prob: float = 0.1
    attention_dropout_prob: float = 0.1
    layer_norm_epsilon: float = 1e-12
    initializer_range: float = 0.02
    pad_token_id: int = 0

    @classmethod
    def base(cls):
        """ERNIE-3.0-base shape."""
        return cls()

    @classmethod
    def tiny(cls):
        return cls(vocab_size=512, hidden_size=64, num_hidden_layers=2,
                   num_attention_heads=4, intermediate_size=128,
                   max_position_embeddings=128, type_vocab_size=2,
                   hidden_dropout_prob=0.0, attention_dropout_prob=0.0)


def _attr(cfg):
    return nn.ParamAttr(
        initializer=nn.initializer.Normal(0.0, cfg.initializer_range))


class ErnieEmbeddings(nn.Layer):
    def __init__(self, cfg: ErnieConfig):
        super().__init__()
        self.word_embeddings = nn.Embedding(
            cfg.vocab_size, cfg.hidden_size, weight_attr=_attr(cfg))
        self.position_embeddings = nn.Embedding(
            cfg.max_position_embeddings, cfg.hidden_size,
            weight_attr=_attr(cfg))
        self.token_type_embeddings = nn.Embedding(
            cfg.type_vocab_size, cfg.hidden_size, weight_attr=_attr(cfg))
        self.use_task_id = cfg.use_task_id
        if cfg.use_task_id:
            self.task_type_embeddings = nn.Embedding(
                cfg.task_type_vocab_size, cfg.hidden_size,
                weight_attr=_attr(cfg))
        self.layer_norm = nn.LayerNorm(cfg.hidden_size,
                                       cfg.layer_norm_epsilon)
        self.dropout = nn.Dropout(cfg.hidden_dropout_prob)

    def forward(self, input_ids, token_type_ids=None, position_ids=None,
                task_type_ids=None):
        s = input_ids.shape[1]
        if position_ids is None:
            position_ids = creation.arange(0, s, dtype="int64")
        x = (self.word_embeddings(input_ids)
             + self.position_embeddings(position_ids))
        if token_type_ids is None:
            token_type_ids = creation.zeros_like(input_ids)
        x = x + self.token_type_embeddings(token_type_ids)
        if self.use_task_id:
            if task_type_ids is None:
                task_type_ids = creation.zeros_like(input_ids)
            x = x + self.task_type_embeddings(task_type_ids)
        return self.dropout(self.layer_norm(x))


class ErnieSelfAttention(nn.Layer):
    def __init__(self, cfg: ErnieConfig):
        super().__init__()
        self.num_heads = cfg.num_attention_heads
        self.head_dim = cfg.hidden_size // cfg.num_attention_heads
        self.qkv = nn.Linear(cfg.hidden_size, 3 * cfg.hidden_size,
                             weight_attr=_attr(cfg))
        self.out = nn.Linear(cfg.hidden_size, cfg.hidden_size,
                             weight_attr=_attr(cfg))
        self.attn_dropout = cfg.attention_dropout_prob

    def forward(self, x, attn_mask=None):
        b, s, e = x.shape
        qkv = M.reshape(self.qkv(x),
                        [b, s, 3, self.num_heads, self.head_dim])
        ctx = F.scaled_dot_product_attention(
            qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2],
            attn_mask=attn_mask, dropout_p=self.attn_dropout,
            training=self.training)
        return self.out(M.reshape(ctx, [b, s, e]))


class ErnieLayer(nn.Layer):
    """Post-norm encoder block (BERT/ERNIE convention)."""

    def __init__(self, cfg: ErnieConfig):
        super().__init__()
        self.attn = ErnieSelfAttention(cfg)
        self.ln1 = nn.LayerNorm(cfg.hidden_size, cfg.layer_norm_epsilon)
        self.fc1 = nn.Linear(cfg.hidden_size, cfg.intermediate_size,
                             weight_attr=_attr(cfg))
        self.fc2 = nn.Linear(cfg.intermediate_size, cfg.hidden_size,
                             weight_attr=_attr(cfg))
        self.ln2 = nn.LayerNorm(cfg.hidden_size, cfg.layer_norm_epsilon)
        self.dropout = nn.Dropout(cfg.hidden_dropout_prob)

    def forward(self, x, attn_mask=None):
        x = self.ln1(x + self.dropout(self.attn(x, attn_mask)))
        h = self.fc2(F.gelu(self.fc1(x)))
        return self.ln2(x + self.dropout(h))


class ErnieModel(nn.Layer):
    def __init__(self, config: ErnieConfig):
        super().__init__()
        self.config = config
        self.embeddings = ErnieEmbeddings(config)
        self.encoder = nn.LayerList(
            [ErnieLayer(config)
             for _ in range(config.num_hidden_layers)])
        self.pooler = nn.Linear(config.hidden_size, config.hidden_size,
                                weight_attr=_attr(config))

    def forward(self, input_ids, token_type_ids=None, position_ids=None,
                attention_mask=None, task_type_ids=None):
        """Returns (sequence_output [B,S,E], pooled_output [B,E]).

        attention_mask: [B, S] with 1 = attend, 0 = padding."""
        mask = None
        if attention_mask is not None:
            # [B, S] -> additive [B, 1, 1, S]
            neg = (1.0 - attention_mask.astype("float32")) * -1e30
            mask = M.reshape(neg, [neg.shape[0], 1, 1, neg.shape[1]])
        x = self.embeddings(input_ids, token_type_ids, position_ids,
                            task_type_ids)
        from ..nn.scan import scan_layers, can_scan
        dropout_live = (self.training
                        and (self.config.hidden_dropout_prob > 0
                             or self.config.attention_dropout_prob > 0))
        if not dropout_live and can_scan(self.encoder):
            x = scan_layers(self.encoder, x,
                            extra_inputs=() if mask is None else (mask,))
        else:
            for layer in self.encoder:
                x = layer(x, mask)
        pooled = F.tanh(self.pooler(x[:, 0]))
        return x, pooled


class ErnieForPretraining(nn.Layer):
    """Masked-LM (tied decoder) + sentence-order prediction heads."""

    def __init__(self, config: ErnieConfig):
        super().__init__()
        self.ernie = ErnieModel(config)
        self.config = config
        cfg = config
        self.mlm_transform = nn.Linear(cfg.hidden_size, cfg.hidden_size,
                                       weight_attr=_attr(cfg))
        self.mlm_ln = nn.LayerNorm(cfg.hidden_size,
                                   cfg.layer_norm_epsilon)
        self.mlm_bias = self.create_parameter(
            [cfg.vocab_size], is_bias=True)
        self.sop_head = nn.Linear(cfg.hidden_size, 2,
                                  weight_attr=_attr(cfg))

    def _mlm_logits(self, hidden):
        from ..ops.linalg import matmul
        h = self.mlm_ln(F.gelu(self.mlm_transform(hidden)))
        return matmul(h, self.ernie.embeddings.word_embeddings.weight,
                      transpose_y=True) + self.mlm_bias

    def forward(self, input_ids, token_type_ids=None, position_ids=None,
                attention_mask=None, masked_lm_labels=None,
                sop_labels=None):
        """masked_lm_labels: [B, S] with -100 = unmasked (ignored).
        Returns (mlm_logits, sop_logits) or the summed loss when labels
        are given (mean over masked positions + mean sop CE)."""
        seq, pooled = self.ernie(input_ids, token_type_ids, position_ids,
                                 attention_mask)
        mlm_logits = self._mlm_logits(seq)
        sop_logits = self.sop_head(pooled)
        if masked_lm_labels is None:
            return mlm_logits, sop_logits
        V = self.config.vocab_size
        loss = F.cross_entropy(M.reshape(mlm_logits, [-1, V]),
                               M.reshape(masked_lm_labels, [-1]),
                               ignore_index=-100)
        if sop_labels is not None:
            loss = loss + F.cross_entropy(sop_logits,
                                          M.reshape(sop_labels, [-1]))
        return loss


class ErnieForMaskedLM(nn.Layer):
    def __init__(self, config: ErnieConfig):
        super().__init__()
        self._pre = ErnieForPretraining(config)
        self.ernie = self._pre.ernie
        self.config = config

    def forward(self, input_ids, token_type_ids=None,
                attention_mask=None, labels=None):
        seq, _ = self.ernie(input_ids, token_type_ids, None,
                            attention_mask)
        logits = self._pre._mlm_logits(seq)
        if labels is None:
            return logits
        V = self.config.vocab_size
        return F.cross_entropy(M.reshape(logits, [-1, V]),
                               M.reshape(labels, [-1]),
                               ignore_index=-100)


class ErnieForSequenceClassification(nn.Layer):
    def __init__(self, config: ErnieConfig, num_classes=2, dropout=None):
        super().__init__()
        self.ernie = ErnieModel(config)
        self.num_classes = num_classes
        p = (config.hidden_dropout_prob if dropout is None else dropout)
        self.dropout = nn.Dropout(p)
        self.classifier = nn.Linear(config.hidden_size, num_classes,
                                    weight_attr=_attr(config))

    def forward(self, input_ids, token_type_ids=None,
                attention_mask=None, labels=None):
        _, pooled = self.ernie(input_ids, token_type_ids, None,
                               attention_mask)
        logits = self.classifier(self.dropout(pooled))
        if labels is None:
            return logits
        return F.cross_entropy(logits, M.reshape(labels, [-1]))
