"""Qwen2 (dense) and Qwen2-MoE model family — BASELINE config 5 workload
(Qwen2-MoE expert-parallel pretrain; reference workloads live in PaddleNLP,
mount empty, no cites).

Architecture: Llama-style decoder with attention QKV bias; the MoE
variant replaces the MLP with top-k routed experts (grouped-matmul bank,
``paddle_tpu.ops.moe``) plus a shared expert scaled by a sigmoid gate —
the Qwen2-MoE block structure. Expert parallelism engages automatically
via the fleet 'expert' mesh axis inside MoELayer."""

from __future__ import annotations

from dataclasses import dataclass

from .. import nn
from ..nn import functional as F
from ..ops import manipulation as M
from ..ops.linalg import matmul
from ..distributed.parallel_layers import (ColumnParallelLinear,
                                           RowParallelLinear,
                                           VocabParallelEmbedding)
from ..incubate.distributed.models.moe import MoELayer
from ..generation import GenerationMixin
from .llama import (rope_with_offset, _alloc_kv_caches,
                    _paged_attention_step)

__all__ = ["Qwen2Config", "Qwen2MoeConfig", "Qwen2ForCausalLM",
           "Qwen2MoeForCausalLMPipe", "Qwen2MoePretrainingCriterion",
           "Qwen2MoeForCausalLM"]


@dataclass
class Qwen2Config:
    vocab_size: int = 151936
    hidden_size: int = 3584
    num_hidden_layers: int = 28
    num_attention_heads: int = 28
    num_key_value_heads: int = 4
    intermediate_size: int = 18944
    max_position_embeddings: int = 32768
    rope_theta: float = 1000000.0
    rms_norm_eps: float = 1e-6
    initializer_range: float = 0.02
    tie_word_embeddings: bool = False
    use_recompute: bool = False
    tensor_parallel: bool = False
    sep_parallel: str | None = None
    # roll the decoder stack into one lax.scan (see nn/scan.py)
    scan_layers: bool = True
    # every k-th layer skips remat entirely (0 = off) — see llama.py
    full_save_interval: int = 0
    # weight-only serving quantization switch — see LlamaConfig
    weight_quant: str | None = None

    @classmethod
    def qwen2_7b(cls):
        return cls()

    @classmethod
    def tiny(cls):
        return cls(vocab_size=256, hidden_size=64, num_hidden_layers=2,
                   num_attention_heads=4, num_key_value_heads=2,
                   intermediate_size=128, max_position_embeddings=128,
                   rope_theta=10000.0)

    @property
    def head_dim(self):
        return self.hidden_size // self.num_attention_heads


@dataclass
class Qwen2MoeConfig(Qwen2Config):
    num_experts: int = 60
    num_experts_per_tok: int = 4
    moe_intermediate_size: int = 1408
    shared_expert_intermediate_size: int = 5632
    norm_topk_prob: bool = False
    router_aux_loss_coef: float = 0.001
    capacity_factor: float = 2.0
    #: MegaBlocks-style dropless dispatch (Pallas grouped matmul): no
    #: capacity, no token drops, and only ~E*128 padding rows of extra
    #: expert compute vs capacity_factor x T*k padded slots. Single
    #: device / GSPMD; under ep_degree > 1 MoELayer keeps the capacity
    #: all-to-all (per-device quotas bound the a2a payload).
    moe_dropless: bool = False

    @classmethod
    def qwen2_moe_a14b(cls):
        return cls(hidden_size=3584, num_hidden_layers=28,
                   num_attention_heads=28, num_key_value_heads=4)

    @classmethod
    def tiny(cls):
        return cls(vocab_size=256, hidden_size=64, num_hidden_layers=2,
                   num_attention_heads=4, num_key_value_heads=2,
                   intermediate_size=128, max_position_embeddings=128,
                   rope_theta=10000.0, num_experts=8,
                   num_experts_per_tok=2, moe_intermediate_size=32,
                   shared_expert_intermediate_size=64)


def _lin(cfg, in_f, out_f, *, column, has_bias=False, gather_output=False):
    init = nn.initializer.Normal(0.0, cfg.initializer_range)
    attr = nn.ParamAttr(initializer=init)
    if cfg.tensor_parallel:
        if column:
            return ColumnParallelLinear(in_f, out_f, weight_attr=attr,
                                        has_bias=has_bias,
                                        gather_output=gather_output)
        return RowParallelLinear(in_f, out_f, weight_attr=attr,
                                 has_bias=has_bias)
    return nn.Linear(in_f, out_f, weight_attr=attr,
                     bias_attr=None if has_bias else False)


class Qwen2Attention(nn.Layer):
    """Llama-style GQA attention with QKV bias (the Qwen2 signature)."""

    def __init__(self, cfg):
        super().__init__()
        self.cfg = cfg
        self.num_heads = cfg.num_attention_heads
        self.num_kv_heads = cfg.num_key_value_heads
        self.head_dim = cfg.head_dim
        self.q_proj = _lin(cfg, cfg.hidden_size,
                           self.num_heads * self.head_dim, column=True,
                           has_bias=True)
        self.k_proj = _lin(cfg, cfg.hidden_size,
                           self.num_kv_heads * self.head_dim, column=True,
                           has_bias=True)
        self.v_proj = _lin(cfg, cfg.hidden_size,
                           self.num_kv_heads * self.head_dim, column=True,
                           has_bias=True)
        self.o_proj = _lin(cfg, self.num_heads * self.head_dim,
                           cfg.hidden_size, column=False)

    def forward(self, x, cache=None, pos=None, tables=None):
        b, s, _ = x.shape
        q = M.reshape(self.q_proj(x), [b, s, self.num_heads, self.head_dim])
        k = M.reshape(self.k_proj(x),
                      [b, s, self.num_kv_heads, self.head_dim])
        v = M.reshape(self.v_proj(x),
                      [b, s, self.num_kv_heads, self.head_dim])
        if cache is not None and tables is not None:
            return _paged_attention_step(self, q, k, v, cache, pos,
                                         tables)
        if cache is not None:
            q = rope_with_offset(q, pos, self.cfg.max_position_embeddings,
                                 self.cfg.rope_theta)
            k = rope_with_offset(k, pos, self.cfg.max_position_embeddings,
                                 self.cfg.rope_theta)
            ctx, k_cache, v_cache = F.sdpa_with_cache(
                q, k, v, cache[0], cache[1], pos)
            ctx = M.reshape(ctx, [b, s, self.num_heads * self.head_dim])
            return self.o_proj(ctx), (k_cache, v_cache)
        from ..incubate.nn.functional import \
            fused_rotary_position_embedding
        q, k, _ = fused_rotary_position_embedding(
            q, k, None, rotary_emb_base=self.cfg.rope_theta)
        if self.cfg.sep_parallel is not None:
            from ..distributed.fleet.meta_parallel.context_parallel import \
                sep_attention
            ctx = sep_attention(q, k, v, causal=True,
                                impl=self.cfg.sep_parallel)
        else:
            ctx = F.scaled_dot_product_attention(q, k, v, is_causal=True)
        ctx = M.reshape(ctx, [b, s, self.num_heads * self.head_dim])
        return self.o_proj(ctx)


class Qwen2MLP(nn.Layer):
    def __init__(self, cfg, intermediate=None):
        super().__init__()
        inter = intermediate or cfg.intermediate_size
        self.gate_proj = _lin(cfg, cfg.hidden_size, inter, column=True)
        self.up_proj = _lin(cfg, cfg.hidden_size, inter, column=True)
        self.down_proj = _lin(cfg, inter, cfg.hidden_size, column=False)

    def forward(self, x):
        return self.down_proj(F.swiglu(self.gate_proj(x), self.up_proj(x)))


class Qwen2MoeBlock(nn.Layer):
    """Routed experts + shared expert with sigmoid gate."""

    def __init__(self, cfg: Qwen2MoeConfig):
        super().__init__()
        self.moe = MoELayer(
            cfg.hidden_size, cfg.moe_intermediate_size, cfg.num_experts,
            gate={"top_k": cfg.num_experts_per_tok,
                  "capacity_factor": cfg.capacity_factor,
                  "norm_topk_prob": cfg.norm_topk_prob,
                  "dropless": getattr(cfg, "moe_dropless", False)})
        self.shared_expert = Qwen2MLP(
            cfg, intermediate=cfg.shared_expert_intermediate_size)
        self.shared_expert_gate = nn.Linear(cfg.hidden_size, 1,
                                            bias_attr=False)

    def forward(self, x):
        routed = self.moe(x)
        shared = self.shared_expert(x)
        gate = F.sigmoid(self.shared_expert_gate(x))
        return routed + gate * shared

    @property
    def aux_loss(self):
        return self.moe.aux_loss


class Qwen2DecoderLayer(nn.Layer):
    def __init__(self, cfg, moe=False):
        super().__init__()
        self.input_layernorm = nn.RMSNorm(cfg.hidden_size, cfg.rms_norm_eps)
        self.self_attn = Qwen2Attention(cfg)
        self.post_attention_layernorm = nn.RMSNorm(cfg.hidden_size,
                                                   cfg.rms_norm_eps)
        self.mlp = Qwen2MoeBlock(cfg) if moe else Qwen2MLP(cfg)

    def forward(self, x, cache=None, pos=None, tables=None):
        if cache is not None:
            attn, new_cache = self.self_attn(self.input_layernorm(x),
                                             cache=cache, pos=pos,
                                             tables=tables)
            x = x + attn
            x = x + self.mlp(self.post_attention_layernorm(x))
            return x, new_cache
        attn = self.self_attn(self.input_layernorm(x))
        from ..framework import flags
        if flags.flag("FLAGS_fused_rmsnorm_residual"):
            # the attention-residual add + post_attention_layernorm
            # pair lowers into ONE fused kernel (identical math; the
            # Pallas kernel on TPU — see models/llama.py's fused carry
            # for the full both-pairs treatment on the flagship stack)
            y, r = F.fused_rms_norm_residual(
                attn, x, self.post_attention_layernorm.weight,
                self.post_attention_layernorm.epsilon)
            return r + self.mlp(y)
        x = x + attn
        x = x + self.mlp(self.post_attention_layernorm(x))
        return x


class _Qwen2Base(nn.Layer, GenerationMixin):
    def __init__(self, cfg, moe: bool):
        super().__init__()
        self.config = cfg
        self._moe = moe
        init = nn.initializer.Normal(0.0, cfg.initializer_range)
        if cfg.tensor_parallel:
            self.embed_tokens = VocabParallelEmbedding(
                cfg.vocab_size, cfg.hidden_size,
                weight_attr=nn.ParamAttr(initializer=init))
        else:
            self.embed_tokens = nn.Embedding(
                cfg.vocab_size, cfg.hidden_size,
                weight_attr=nn.ParamAttr(initializer=init))
        self.layers = nn.LayerList([Qwen2DecoderLayer(cfg, moe=moe)
                                    for _ in range(cfg.num_hidden_layers)])
        self.norm = nn.RMSNorm(cfg.hidden_size, cfg.rms_norm_eps)
        self.lm_head = _lin(cfg, cfg.hidden_size, cfg.vocab_size,
                            column=True, gather_output=True) \
            if not cfg.tie_word_embeddings else None

    def init_kv_cache(self, batch_size, max_length, dtype=None):
        if dtype is None:
            dtype = next(iter(self.parameters())).dtype
        return _alloc_kv_caches(self.config, batch_size, max_length, dtype)

    def forward(self, input_ids, labels=None, caches=None, pos=None,
                tables=None):
        if self._moe and self.training and self.config.use_recompute \
                and self.config.router_aux_loss_coef:
            # raised here (where recompute actually wraps the layers),
            # not at construction: inference-only use of a training
            # config is fine. Without this check the failure is an
            # opaque escaped-tracer error deep in tracing.
            raise ValueError(
                "router_aux_loss_coef > 0 with use_recompute=True is "
                "unsupported for training: the per-layer aux-loss "
                "attribute cannot cross the jax.checkpoint boundary "
                "(the stored tracer would leak). Set "
                "router_aux_loss_coef=0.0 or use_recompute=False.")
        x = self.embed_tokens(input_ids)
        if caches is not None:
            new_caches = []
            # 2 pools per layer, or 4 under quantized KV (ISSUE 20)
            stride = len(caches) // len(self.layers)
            for i, layer in enumerate(self.layers):
                x, kv = layer(
                    x, cache=tuple(caches[stride * i:stride * (i + 1)]),
                    pos=pos, tables=tables)
                new_caches.extend(kv)
            hidden = self.norm(x)
            logits = self.lm_head(hidden) if self.lm_head is not None else \
                matmul(hidden, self.embed_tokens.weight, transpose_y=True)
            return logits, new_caches
        from ..nn.scan import scan_layers as _scan, can_scan
        # MoE stacks never scan: per-layer aux_loss attributes are read
        # eagerly after the stack (and experts route via shard_map)
        if getattr(self.config, "scan_layers", True) and \
                not self._moe and can_scan(self.layers):
            x = _scan(self.layers, x,
                      remat=self.config.use_recompute and self.training,
                      full_save_interval=getattr(
                          self.config, "full_save_interval", 0))
        else:
            # remat DOSE (same knob as LlamaConfig.full_save_interval):
            # every k-th layer keeps activations whole instead of
            # recomputing — spend leftover HBM on backward speed
            fs = max(int(getattr(self.config, "full_save_interval", 0)),
                     0)
            for i, layer in enumerate(self.layers):
                if self.config.use_recompute and self.training and \
                        not (fs and i % fs == fs - 1):
                    from ..incubate.recompute import recompute
                    x = recompute(layer, x)
                else:
                    x = layer(x)
        hidden = self.norm(x)
        if self.lm_head is not None:
            logits = self.lm_head(hidden)
        else:
            logits = matmul(hidden, self.embed_tokens.weight,
                            transpose_y=True)
        if labels is None:
            return logits
        shift_logits = logits[:, :-1, :]
        shift_labels = labels[:, 1:]
        loss = F.cross_entropy(
            M.reshape(shift_logits, [-1, self.config.vocab_size]),
            M.reshape(shift_labels, [-1]))
        if self._moe and self.config.router_aux_loss_coef:
            # NOTE: per-layer aux attributes cannot cross a jax.checkpoint
            # boundary (use_recompute wraps each layer; the stored tracer
            # would leak) — run aux-weighted training without recompute,
            # or fold aux out (coef=0)
            coef = self.config.router_aux_loss_coef
            for layer in self.layers:
                aux = layer.mlp.aux_loss
                if aux is not None:
                    loss = loss + coef * aux
        return logits, loss


class Qwen2ForCausalLM(_Qwen2Base):
    def __init__(self, config: Qwen2Config):
        super().__init__(config, moe=False)


class Qwen2MoeForCausalLM(_Qwen2Base):
    def __init__(self, config: Qwen2MoeConfig):
        super().__init__(config, moe=True)


# ---------------------------------------------------------------------------
# Pipeline-parallel Qwen2-MoE: the ep x pp composition workload (SURVEY.md
# §2.3 EP row — expert all-to-all dispatch inside the compiled pipeline
# program). Construction order matches _Qwen2Base exactly so same-seed
# models draw identical initial weights (the parity-test basis).
# ---------------------------------------------------------------------------


# The prologue/epilogue/criterion are duck-typed on config fields that
# Qwen2MoeConfig shares with LlamaConfig (vocab_size, hidden_size,
# initializer_range, rms_norm_eps, tensor_parallel) — reuse the llama
# pipe classes rather than duplicating them.
from .llama import (LlamaEmbeddingPipe as Qwen2EmbeddingPipe,
                    LlamaHeadPipe as Qwen2HeadPipe)


class Qwen2MoeDecoderLayerPipe(Qwen2DecoderLayer):
    """Decoder stage for the pipeline body; carries ``config`` so the
    engine can detect MoE/sep participation."""

    def __init__(self, cfg):
        super().__init__(cfg, moe=True)
        self.config = cfg


# Shifted next-token CE — the PLAIN language-model loss (the llama
# criterion is duck-typed on vocab_size only). The router aux loss is an
# eager per-layer attribute in the monolithic model and cannot cross the
# compiled pipeline boundary; pipelined MoE training therefore runs with
# aux folded out (router_aux_loss_coef=0 parity — load balance still
# trains through the dispatch gradient).
from .llama import LlamaPretrainingCriterion as Qwen2MoePretrainingCriterion


def Qwen2MoeForCausalLMPipe(config, **pipeline_kwargs):
    """Build the pipelined Qwen2-MoE as a ``PipelineLayer`` (embedding
    prologue / uniform MoE decoder body / norm+head epilogue)."""
    from ..distributed.fleet.meta_parallel import LayerDesc, PipelineLayer

    descs = [LayerDesc(Qwen2EmbeddingPipe, config)] + \
        [LayerDesc(Qwen2MoeDecoderLayerPipe, config)
         for _ in range(config.num_hidden_layers)] + \
        [LayerDesc(Qwen2HeadPipe, config)]
    pipeline_kwargs.setdefault("loss_fn",
                               Qwen2MoePretrainingCriterion(config))
    return PipelineLayer(descs, **pipeline_kwargs)
