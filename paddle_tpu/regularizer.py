"""paddle.regularizer — L1Decay / L2Decay.

Upstream (``python/paddle/regularizer.py``, UNVERIFIED) attaches these to
``ParamAttr`` or passes them as ``weight_decay=`` on optimizers; the decay
is folded into the gradient before the update rule. Same semantics here —
the fold happens in ``Optimizer._apply_decay`` inside the (traced) step, so
XLA fuses it into the optimizer kernel.
"""

from __future__ import annotations

import jax.numpy as jnp


class WeightDecayRegularizer:
    coeff: float = 0.0

    def __call__(self, param, grad):
        raise NotImplementedError


def _data_of(x):
    return x._data if hasattr(x, "_data") else jnp.asarray(x)


class L1Decay(WeightDecayRegularizer):
    """L1 weight decay: grad += coeff * sign(param)."""

    def __init__(self, coeff=0.0):
        self.coeff = float(coeff)

    def __call__(self, param, grad):
        p, g = _data_of(param), _data_of(grad)
        out = g + self.coeff * jnp.sign(p).astype(g.dtype)
        return type(grad)(out) if hasattr(grad, "_data") else out

    def __repr__(self):
        return f"L1Decay(coeff={self.coeff})"


class L2Decay(WeightDecayRegularizer):
    """L2 weight decay: grad += coeff * param."""

    def __init__(self, coeff=0.0):
        self.coeff = float(coeff)

    def __call__(self, param, grad):
        p, g = _data_of(param), _data_of(grad)
        out = g + self.coeff * p.astype(g.dtype)
        return type(grad)(out) if hasattr(grad, "_data") else out

    def __repr__(self):
        return f"L2Decay(coeff={self.coeff})"


__all__ = ["WeightDecayRegularizer", "L1Decay", "L2Decay"]
