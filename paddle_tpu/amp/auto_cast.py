"""Automatic mixed precision — the role of ``paddle.amp.auto_cast`` plus the
C++ per-op cast insertion (``paddle/fluid/eager/amp_*``, UNVERIFIED).

TPU-first: bf16 is the native mixed-precision dtype (no loss scaling needed);
fp16 ('O1'/'O2' with GradScaler) is supported for source parity. The cast
policy is applied inside the hot ops (matmul/conv/attention) rather than by
rewriting every op — the XLA fusion pass makes the surrounding elementwise
dtype churn free.
"""

from __future__ import annotations

import contextlib
import threading

import jax.numpy as jnp

from ..framework.core import Tensor, to_jax_dtype, is_floating

__all__ = ["auto_cast", "amp_guard", "is_auto_cast_enabled", "amp_state",
           "maybe_cast_matmul", "white_list", "black_list", "decorate"]

# ops always cast to low precision under AMP (mirrors paddle's white list)
white_list = {"matmul", "conv2d", "conv1d", "conv3d", "einsum", "mm", "bmm",
              "attention", "linear"}
# ops kept in fp32 (reductions that need range)
black_list = {"softmax", "log_softmax", "layer_norm", "cross_entropy",
              "exp", "log", "mean", "sum", "norm"}


class _AmpState(threading.local):
    def __init__(self):
        self.enabled = False
        self.dtype = jnp.bfloat16
        self.level = "O1"
        self.custom_white = set()
        self.custom_black = set()


_state = _AmpState()


def amp_state() -> _AmpState:
    return _state


def is_auto_cast_enabled() -> bool:
    return _state.enabled


@contextlib.contextmanager
def auto_cast(enable=True, custom_white_list=None, custom_black_list=None,
              level="O1", dtype="bfloat16", use_promote=True):
    prev = (_state.enabled, _state.dtype, _state.level,
            _state.custom_white, _state.custom_black)
    _state.enabled = bool(enable)
    _state.dtype = to_jax_dtype(dtype)
    _state.level = level
    _state.custom_white = set(custom_white_list or ())
    _state.custom_black = set(custom_black_list or ())
    try:
        yield
    finally:
        (_state.enabled, _state.dtype, _state.level,
         _state.custom_white, _state.custom_black) = prev


amp_guard = auto_cast


def maybe_cast_matmul(x: Tensor, y: Tensor):
    """Cast matmul operands to the AMP dtype when auto_cast is active."""
    if not _state.enabled:
        return x, y
    lo = _state.dtype

    def cast(t):
        if isinstance(t, Tensor) and is_floating(t.dtype) and t.dtype != lo:
            from ..ops.manipulation import cast as cast_op
            return cast_op(t, lo)
        return t
    return cast(x), cast(y)


def maybe_cast(t, op_name: str):
    """Generic AMP cast hook for a named op."""
    if not _state.enabled:
        return t
    wl = (white_list | _state.custom_white) - _state.custom_black
    if op_name not in wl:
        return t
    if isinstance(t, Tensor) and is_floating(t.dtype) \
            and t.dtype != _state.dtype:
        from ..ops.manipulation import cast as cast_op
        return cast_op(t, _state.dtype)
    return t


def decorate(models, optimizers=None, level="O2", dtype="bfloat16",
             master_weight=None, save_dtype=None):
    """``paddle.amp.decorate`` — for O2, cast model params to the AMP dtype.
    Optimizer master weights are handled by the optimizer (it keeps fp32
    copies when params are low-precision)."""
    single = not isinstance(models, (list, tuple))
    model_list = [models] if single else list(models)
    if level == "O2":
        lo = to_jax_dtype(dtype)
        for m in model_list:
            for p in m.parameters():
                if is_floating(p.dtype):
                    p.set_data(p._data.astype(lo))
    if optimizers is None:
        return models
    return models, optimizers
