from .auto_cast import auto_cast, amp_guard, is_auto_cast_enabled, \
    amp_state, white_list, black_list, decorate
from .grad_scaler import GradScaler, AmpScaler

__all__ = ["auto_cast", "amp_guard", "GradScaler", "AmpScaler", "decorate",
           "is_auto_cast_enabled", "white_list", "black_list"]


def is_float16_supported(device=None):
    """fp16 compute support. TPU MXU natively computes bf16; fp16 works
    via XLA conversion, so the API answers True on accelerator backends."""
    import jax
    return jax.devices()[0].platform != "cpu"


def is_bfloat16_supported(device=None):
    return True   # bf16 is the native TPU compute dtype


class debugging:
    """paddle.amp.debugging namespace: numerics checking maps to jax's
    debug_nans/debug_infs flags (TensorChecker role)."""

    @staticmethod
    def enable_operator_stats_collection():
        from ..utils import monitor
        monitor.enable_op_stats()

    @staticmethod
    def disable_operator_stats_collection():
        from ..utils import monitor
        monitor.disable_op_stats()

    @staticmethod
    def collect_operator_stats():
        """Context manager: count ops by (name, dtype) within the block
        and print the summary on exit (amp.debugging parity)."""
        import contextlib

        @contextlib.contextmanager
        def ctx():
            from ..utils import monitor
            monitor.enable_op_stats()
            try:
                yield
            finally:
                monitor.disable_op_stats()
                summary = monitor.op_stats_summary()
                print("operator stats:")
                for k, v in summary.items():
                    print(f"  {k}: {v}")
        return ctx()

    @staticmethod
    def check_numerics(tensor, op_type="", var_name="",
                       debug_mode=None):
        import jax.numpy as jnp
        import numpy as np
        from ..framework.core import Tensor
        a = tensor._data if isinstance(tensor, Tensor) else tensor
        bad = int(jnp.sum(~jnp.isfinite(a.astype(jnp.float32))))
        if bad:
            raise RuntimeError(
                f"check_numerics: {bad} non-finite element(s) in "
                f"{op_type or 'tensor'} {var_name}")
        return tensor

    @staticmethod
    def enable_check_nan_inf():
        import jax
        jax.config.update("jax_debug_nans", True)

    @staticmethod
    def disable_check_nan_inf():
        import jax
        jax.config.update("jax_debug_nans", False)


__all__ += ["is_float16_supported", "is_bfloat16_supported", "debugging"]
