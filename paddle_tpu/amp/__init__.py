from .auto_cast import auto_cast, amp_guard, is_auto_cast_enabled, \
    amp_state, white_list, black_list, decorate
from .grad_scaler import GradScaler, AmpScaler

__all__ = ["auto_cast", "amp_guard", "GradScaler", "AmpScaler", "decorate",
           "is_auto_cast_enabled", "white_list", "black_list"]


def is_float16_supported(device=None):
    """fp16 compute support. TPU MXU natively computes bf16; fp16 works
    via XLA conversion, so the API answers True on accelerator backends."""
    import jax
    return jax.devices()[0].platform != "cpu"


def is_bfloat16_supported(device=None):
    return True   # bf16 is the native TPU compute dtype


class debugging:
    """paddle.amp.debugging namespace: numerics checking maps to jax's
    debug_nans/debug_infs flags (TensorChecker role)."""

    @staticmethod
    def enable_operator_stats_collection():
        from ..utils import monitor
        monitor.enable_op_stats()

    @staticmethod
    def disable_operator_stats_collection():
        from ..utils import monitor
        monitor.disable_op_stats()

    @staticmethod
    def collect_operator_stats():
        """Context manager: count ops by (name, dtype) within the block
        and print the summary on exit (amp.debugging parity)."""
        import contextlib

        @contextlib.contextmanager
        def ctx():
            from ..utils import monitor
            monitor.enable_op_stats()
            try:
                yield
            finally:
                monitor.disable_op_stats()
                summary = monitor.op_stats_summary()
                print("operator stats:")
                for k, v in summary.items():
                    print(f"  {k}: {v}")
        return ctx()

    @staticmethod
    def check_numerics(tensor, op_type="", var_name="",
                       debug_mode=None):
        import jax.numpy as jnp
        import numpy as np
        from ..framework.core import Tensor
        a = tensor._data if isinstance(tensor, Tensor) else tensor
        bad = int(jnp.sum(~jnp.isfinite(a.astype(jnp.float32))))
        if bad:
            raise RuntimeError(
                f"check_numerics: {bad} non-finite element(s) in "
                f"{op_type or 'tensor'} {var_name}")
        return tensor

    @staticmethod
    def enable_check_nan_inf():
        import jax
        jax.config.update("jax_debug_nans", True)

    @staticmethod
    def disable_check_nan_inf():
        import jax
        jax.config.update("jax_debug_nans", False)

    class DebugMode:
        """amp.debugging.DebugMode enum parity (the TensorChecker
        granularity knobs; CHECK_ALL is the only behavior here — jax
        debug_nans checks every op)."""
        CHECK_NAN_INF_AND_ABORT = 0
        CHECK_NAN_INF = 1
        CHECK_ALL_FOR_OVERFLOW = 2
        CHECK_ALL = 3
        CHECK_ALL_AND_ABORT = 4
        DUMP_ALL = 5

    @staticmethod
    def check_layer_numerics(layer):
        """Decorates a Layer so every forward output is numerics-checked
        (amp.debugging.check_layer_numerics parity)."""
        orig = layer.forward

        def wrapped(*a, **k):
            out = orig(*a, **k)
            from ..framework.core import Tensor
            outs = out if isinstance(out, (tuple, list)) else (out,)
            for t in outs:
                if isinstance(t, Tensor):
                    debugging.check_numerics(
                        t, op_type=type(layer).__name__)
            return out
        layer.forward = wrapped
        return layer

    @staticmethod
    def compare_accuracy(dump_path, another_dump_path, output_filename,
                         loss_scale=1.0, dump_all_module=False):
        """amp.debugging.compare_accuracy parity: diff two op-stats JSONL
        dumps (from collect_operator_stats runs) and write a report of
        ops whose counts/dtypes diverge."""
        import json
        import os

        def load(path):
            rows = {}
            with open(path) as fh:
                for line in fh:
                    if line.strip():
                        rec = json.loads(line)
                        rows[rec.get("op", repr(rec))] = rec
            return rows

        a, b = load(dump_path), load(another_dump_path)
        report = []
        for op in sorted(set(a) | set(b)):
            ra, rb = a.get(op), b.get(op)
            if ra != rb:
                report.append({"op": op, "run1": ra, "run2": rb})
        os.makedirs(os.path.dirname(output_filename) or ".",
                    exist_ok=True)
        with open(output_filename, "w") as fh:
            json.dump(report, fh, indent=1)
        return report


__all__ += ["is_float16_supported", "is_bfloat16_supported", "debugging"]
