from .auto_cast import auto_cast, amp_guard, is_auto_cast_enabled, \
    amp_state, white_list, black_list, decorate
from .grad_scaler import GradScaler, AmpScaler

__all__ = ["auto_cast", "amp_guard", "GradScaler", "AmpScaler", "decorate",
           "is_auto_cast_enabled", "white_list", "black_list"]
