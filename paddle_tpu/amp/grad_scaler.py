"""Dynamic loss scaling — ``paddle.amp.GradScaler`` parity (UNVERIFIED path
python/paddle/amp/grad_scaler.py; kernels ``check_finite_and_unscale`` /
``update_loss_scaling`` in phi).

On TPU bf16 training doesn't need loss scaling; this exists for fp16 parity
and follows the same dynamic-scale algorithm (grow after N good steps, shrink
on inf/nan, skip the step)."""

from __future__ import annotations

import jax.numpy as jnp

from ..framework.core import Tensor, no_grad

__all__ = ["GradScaler", "AmpScaler"]


class GradScaler:
    def __init__(self, enable=True, init_loss_scaling=2.0 ** 15,
                 incr_ratio=2.0, decr_ratio=0.5, incr_every_n_steps=2000,
                 decr_every_n_nan_or_inf=1, use_dynamic_loss_scaling=True):
        self._enable = enable
        self._scale = float(init_loss_scaling)
        self._incr_ratio = incr_ratio
        self._decr_ratio = decr_ratio
        self._incr_every_n_steps = incr_every_n_steps
        self._decr_every_n = decr_every_n_nan_or_inf
        self._dynamic = use_dynamic_loss_scaling
        self._found_inf = False
        self._found_inf_dev = None   # device bool from the last unscale_
        # guards the unscale_-then-step pattern against double unscaling
        self._unscaled_since_step = False
        # The DEVICE owns the dynamic-scaling state (scale + good/bad
        # step counters) as persistable scalars, the optimizer
        # _lr_state/_step_state pattern: a to_static-compiled train
        # step reads the CURRENT scale as state input (no baked
        # trace-time constant) and update()'s grow/shrink runs as
        # traced jnp math — so the scale keeps growing across compiled
        # replays, where python counter increments would never execute.
        self._scale_state = Tensor(jnp.asarray(self._scale, jnp.float32))
        self._scale_state.persistable = True
        self._scale_state.name = "loss_scaling"
        self._good_state = Tensor(jnp.asarray(0, jnp.int32))
        self._good_state.persistable = True
        self._good_state.name = "loss_scaling_good_steps"
        self._bad_state = Tensor(jnp.asarray(0, jnp.int32))
        self._bad_state.persistable = True
        self._bad_state.name = "loss_scaling_bad_steps"

    def _sync_scale_state(self) -> None:
        """Push the python-side scale into device state (explicit
        setters only — per-step syncing would stomp device-side
        growth)."""
        from ..framework.core import trace_clean
        if trace_clean():
            self._scale_state.set_data(
                jnp.asarray(self._scale, jnp.float32))

    def _read_scalar(self, t, cast):
        """Host read of a device state scalar (outside traces only)."""
        import numpy as np
        return cast(np.asarray(t._data))

    def is_enable(self) -> bool:
        return self._enable

    def is_use_dynamic_loss_scaling(self) -> bool:
        return self._dynamic

    def get_loss_scaling(self) -> float:
        from ..framework.core import trace_clean
        if trace_clean():
            self._scale = self._read_scalar(self._scale_state, float)
        return self._scale

    def set_init_loss_scaling(self, v) -> None:
        self._scale = float(v)
        self._sync_scale_state()

    def scale(self, var: Tensor) -> Tensor:
        if not self._enable:
            return var
        # read the state tensor (not a python float) so a compiled
        # step traces a state read — per-call live scale; cast to the
        # loss dtype so an fp16/bf16 loss is not silently promoted to
        # f32 (the old weakly-typed python float preserved it)
        return var * Tensor(self._scale_state.jax().astype(
            var._data.dtype))

    def unscale_(self, optimizer) -> None:
        if not self._enable:
            return
        if self._unscaled_since_step:
            raise RuntimeError(
                "GradScaler.unscale_() already called since the last "
                "step()/update(); calling it twice would double-unscale "
                "the gradients")
        self._unscaled_since_step = True
        inv = 1.0 / self._scale_state.jax()
        found = jnp.asarray(False)
        with no_grad():
            for p in optimizer._parameter_list:
                if p.grad is None:
                    continue
                # keep the grad's own (possibly low-precision) dtype
                g = p.grad._data * inv.astype(p.grad._data.dtype)
                found = jnp.logical_or(found, ~jnp.all(jnp.isfinite(g)))
                p.grad.set_data(g)
        # the raw device bool feeds update()'s traced counter math;
        # bool() THROUGH the Tensor funnel is a GUARDED branch decision
        # under to_static — an inf/nan flip discards the compiled run
        # and re-runs eagerly (correct skip semantics) instead of
        # committing a stale-branch update. bool() on the raw array
        # would be an unguardable hard graph break.
        self._found_inf_dev = found
        self._found_inf = bool(Tensor(found))

    def step(self, optimizer) -> None:
        if not self._enable:
            optimizer.step()
            return
        if not self._unscaled_since_step:
            self.unscale_(optimizer)
        if not self._found_inf:
            optimizer.step()
        self.update()

    def minimize(self, optimizer, scaled_loss) -> None:
        self.step(optimizer)

    def update(self) -> None:
        """Dynamic-scale adjustment as TRACED device math (no python
        counters): exactly the reference algorithm — on overflow bump
        bad_steps, zero good_steps, shrink after decr_every_n bad
        steps; on a clean step bump good_steps, zero bad_steps, grow
        after incr_every_n good steps. Because it is jnp math over
        persistable state, compiled replays keep growing the scale —
        python `+= 1` bodies would only ever run on the trace."""
        self._unscaled_since_step = False
        if not (self._enable and self._dynamic):
            return
        found = self._found_inf_dev
        if found is None:
            found = jnp.asarray(bool(self._found_inf))
        scale = self._scale_state.jax()
        good = self._good_state.jax()
        bad = self._bad_state.jax()
        bad_next = jnp.where(found, bad + 1, 0)
        good_next = jnp.where(found, 0, good + 1)
        shrink = bad_next >= self._decr_every_n       # only when found
        grow = good_next >= self._incr_every_n_steps  # only when clean
        self._scale_state.set_data(jnp.where(
            shrink, jnp.maximum(scale * self._decr_ratio, 1.0),
            jnp.where(grow, scale * self._incr_ratio, scale)))
        self._bad_state.set_data(jnp.where(shrink, 0, bad_next))
        self._good_state.set_data(jnp.where(grow, 0, good_next))
        self._found_inf = False
        self._found_inf_dev = None

    # host-facing views of the device counters (state_dict parity)

    @property
    def _good_steps(self) -> int:
        return self._read_scalar(self._good_state, int)

    @property
    def _bad_steps(self) -> int:
        return self._read_scalar(self._bad_state, int)

    def state_dict(self) -> dict:
        return {"scale": self.get_loss_scaling(),
                "incr_ratio": self._incr_ratio,
                "decr_ratio": self._decr_ratio,
                "incr_count": self._good_steps,
                "decr_count": self._bad_steps}

    def load_state_dict(self, state: dict) -> None:
        self._scale = state.get("scale", self._scale)
        self._good_state.set_data(
            jnp.asarray(int(state.get("incr_count", 0)), jnp.int32))
        self._bad_state.set_data(
            jnp.asarray(int(state.get("decr_count", 0)), jnp.int32))
        self._sync_scale_state()


AmpScaler = GradScaler
