"""Dynamic loss scaling — ``paddle.amp.GradScaler`` parity (UNVERIFIED path
python/paddle/amp/grad_scaler.py; kernels ``check_finite_and_unscale`` /
``update_loss_scaling`` in phi).

On TPU bf16 training doesn't need loss scaling; this exists for fp16 parity
and follows the same dynamic-scale algorithm (grow after N good steps, shrink
on inf/nan, skip the step)."""

from __future__ import annotations

import jax.numpy as jnp

from ..framework.core import Tensor, no_grad

__all__ = ["GradScaler", "AmpScaler"]


class GradScaler:
    def __init__(self, enable=True, init_loss_scaling=2.0 ** 15,
                 incr_ratio=2.0, decr_ratio=0.5, incr_every_n_steps=2000,
                 decr_every_n_nan_or_inf=1, use_dynamic_loss_scaling=True):
        self._enable = enable
        self._scale = float(init_loss_scaling)
        self._incr_ratio = incr_ratio
        self._decr_ratio = decr_ratio
        self._incr_every_n_steps = incr_every_n_steps
        self._decr_every_n = decr_every_n_nan_or_inf
        self._dynamic = use_dynamic_loss_scaling
        self._good_steps = 0
        self._bad_steps = 0
        self._found_inf = False
        # guards the unscale_-then-step pattern against double unscaling
        self._unscaled_since_step = False

    def is_enable(self) -> bool:
        return self._enable

    def is_use_dynamic_loss_scaling(self) -> bool:
        return self._dynamic

    def get_loss_scaling(self) -> float:
        return self._scale

    def set_init_loss_scaling(self, v) -> None:
        self._scale = float(v)

    def scale(self, var: Tensor) -> Tensor:
        if not self._enable:
            return var
        return var * self._scale

    def unscale_(self, optimizer) -> None:
        if not self._enable:
            return
        if self._unscaled_since_step:
            raise RuntimeError(
                "GradScaler.unscale_() already called since the last "
                "step()/update(); calling it twice would double-unscale "
                "the gradients")
        self._unscaled_since_step = True
        inv = 1.0 / self._scale
        found = jnp.asarray(False)
        with no_grad():
            for p in optimizer._parameter_list:
                if p.grad is None:
                    continue
                g = p.grad._data * inv
                found = jnp.logical_or(found, ~jnp.all(jnp.isfinite(g)))
                p.grad.set_data(g)
        self._found_inf = bool(found)

    def step(self, optimizer) -> None:
        if not self._enable:
            optimizer.step()
            return
        if not self._unscaled_since_step:
            self.unscale_(optimizer)
        if not self._found_inf:
            optimizer.step()
        self.update()

    def minimize(self, optimizer, scaled_loss) -> None:
        self.step(optimizer)

    def update(self) -> None:
        self._unscaled_since_step = False
        if not (self._enable and self._dynamic):
            return
        if self._found_inf:
            self._bad_steps += 1
            self._good_steps = 0
            if self._bad_steps >= self._decr_every_n:
                self._scale = max(self._scale * self._decr_ratio, 1.0)
                self._bad_steps = 0
        else:
            self._good_steps += 1
            self._bad_steps = 0
            if self._good_steps >= self._incr_every_n_steps:
                self._scale *= self._incr_ratio
                self._good_steps = 0
        self._found_inf = False

    def state_dict(self) -> dict:
        return {"scale": self._scale, "incr_ratio": self._incr_ratio,
                "decr_ratio": self._decr_ratio,
                "incr_count": self._good_steps, "decr_count": self._bad_steps}

    def load_state_dict(self, state: dict) -> None:
        self._scale = state.get("scale", self._scale)
        self._good_steps = state.get("incr_count", 0)
        self._bad_steps = state.get("decr_count", 0)


AmpScaler = GradScaler
