"""``paddle.metric`` (python/paddle/metric/ parity, UNVERIFIED)."""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from ..framework.core import Tensor
from ..ops.common import as_tensor

__all__ = ["Metric", "Accuracy", "Precision", "Recall", "Auc", "accuracy"]


def accuracy(input, label, k=1, correct=None, total=None, name=None):
    input, label = as_tensor(input), as_tensor(label)
    topk = jnp.argsort(-input._data, axis=-1)[..., :k]
    lab = label._data
    if lab.ndim == topk.ndim:
        lab = lab[..., 0]
    hit = jnp.any(topk == lab[..., None], axis=-1)
    return Tensor(jnp.mean(hit.astype(jnp.float32), keepdims=True))


class Metric:
    def reset(self):
        raise NotImplementedError

    def update(self, *args):
        raise NotImplementedError

    def accumulate(self):
        raise NotImplementedError

    def name(self):
        return self.__class__.__name__.lower()

    def compute(self, *args):
        return args


class Accuracy(Metric):
    def __init__(self, topk=(1,), name=None):
        self.topk = topk if isinstance(topk, (list, tuple)) else (topk,)
        self._name = name or "acc"
        self.reset()

    def reset(self):
        self.total = [0.0] * len(self.topk)
        self.count = [0] * len(self.topk)

    def compute(self, pred, label, *args):
        pred = as_tensor(pred)
        label = as_tensor(label)
        maxk = max(self.topk)
        idx = jnp.argsort(-pred._data, axis=-1)[..., :maxk]
        lab = label._data
        if lab.ndim == idx.ndim:
            lab = lab[..., 0]
        correct = (idx == lab[..., None])
        return Tensor(correct.astype(jnp.float32))

    def update(self, correct, *args):
        c = np.asarray(correct._data if isinstance(correct, Tensor)
                       else correct)
        n = c.shape[0] if c.ndim else 1
        accs = []
        for i, k in enumerate(self.topk):
            hit = c[..., :k].any(-1).sum()
            self.total[i] += float(hit)
            self.count[i] += n
            accs.append(float(hit) / max(n, 1))
        return accs[0] if len(accs) == 1 else accs

    def accumulate(self):
        out = [t / max(c, 1) for t, c in zip(self.total, self.count)]
        return out[0] if len(out) == 1 else out

    def name(self):
        if len(self.topk) == 1:
            return self._name
        return [f"{self._name}_top{k}" for k in self.topk]


class Precision(Metric):
    def __init__(self, name=None):
        self._name = name or "precision"
        self.reset()

    def reset(self):
        self.tp = 0
        self.fp = 0

    def update(self, preds, labels):
        p = np.asarray(preds._data if isinstance(preds, Tensor) else preds)
        l = np.asarray(labels._data if isinstance(labels, Tensor)
                       else labels)
        pred_pos = (p > 0.5).reshape(-1)
        lab = l.reshape(-1).astype(bool)
        self.tp += int((pred_pos & lab).sum())
        self.fp += int((pred_pos & ~lab).sum())

    def accumulate(self):
        denom = self.tp + self.fp
        return self.tp / denom if denom else 0.0

    def name(self):
        return self._name


class Recall(Metric):
    def __init__(self, name=None):
        self._name = name or "recall"
        self.reset()

    def reset(self):
        self.tp = 0
        self.fn = 0

    def update(self, preds, labels):
        p = np.asarray(preds._data if isinstance(preds, Tensor) else preds)
        l = np.asarray(labels._data if isinstance(labels, Tensor)
                       else labels)
        pred_pos = (p > 0.5).reshape(-1)
        lab = l.reshape(-1).astype(bool)
        self.tp += int((pred_pos & lab).sum())
        self.fn += int((~pred_pos & lab).sum())

    def accumulate(self):
        denom = self.tp + self.fn
        return self.tp / denom if denom else 0.0

    def name(self):
        return self._name


class Auc(Metric):
    def __init__(self, curve="ROC", num_thresholds=4095, name=None):
        self._name = name or "auc"
        self.num_thresholds = num_thresholds
        self.reset()

    def reset(self):
        self._stat_pos = np.zeros(self.num_thresholds + 1)
        self._stat_neg = np.zeros(self.num_thresholds + 1)

    def update(self, preds, labels):
        p = np.asarray(preds._data if isinstance(preds, Tensor) else preds)
        l = np.asarray(labels._data if isinstance(labels, Tensor)
                       else labels).reshape(-1)
        pos_prob = p[:, 1] if p.ndim == 2 and p.shape[1] == 2 else \
            p.reshape(-1)
        bins = np.minimum((pos_prob * self.num_thresholds).astype(int),
                          self.num_thresholds)
        for b, y in zip(bins, l):
            if y:
                self._stat_pos[b] += 1
            else:
                self._stat_neg[b] += 1

    def accumulate(self):
        tot_pos = self._stat_pos.sum()
        tot_neg = self._stat_neg.sum()
        if tot_pos == 0 or tot_neg == 0:
            return 0.0
        area = 0.0
        pos = neg = 0.0
        for i in range(self.num_thresholds, -1, -1):
            new_pos = pos + self._stat_pos[i]
            new_neg = neg + self._stat_neg[i]
            area += (new_neg - neg) * (pos + new_pos) / 2
            pos, neg = new_pos, new_neg
        return area / (tot_pos * tot_neg)

    def name(self):
        return self._name
