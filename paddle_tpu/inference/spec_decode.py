"""Speculative decoding draft sources + distribution-exact verification.

Engine-level speculative decoding (ISSUE 18) through the EXISTING ragged
mixed pass: a drafting decode slot stops riding its pending token as a
length-1 query and instead rides ``1 + K`` tokens — the pending token in
column 0 (same contract as the plain unified step) followed by ``K``
draft tokens — so verification is just a short "prefill-shaped" chunk
through ``ragged_paged_attention``. No new kernel; no new compiled
shapes beyond the ``[num_slots, prefill_chunk]`` ladder already tuned
(``K + 1 <= prefill_chunk`` is enforced at the engine ctor).

This module owns the two halves that are independent of the engine's
scheduler:

- **Draft sources** (the strategy seam): given the engine's host view of
  each drafting slot, propose up to K tokens per slot.

  * :class:`NGramDraftSource` — prompt-lookup: match the last ``n``
    known tokens of ``prompt + emitted`` against every earlier position
    of the same history and propose the continuation. Pure host work,
    zero extra device programs.
  * :class:`SelfSpecDraftSource` — self-speculation: re-run the SAME
    model with a configurable subset of layers skipped as its own cheap
    draft model (one compiled K-step greedy scan whose functionally
    updated KV pools are DISCARDED — draft state never touches the
    verified cache).

- **Rejection sampling** (:func:`rejection_sample`): the classic
  speculative-sampling acceptance rule specialized to point-mass drafts
  (both sources propose single tokens, i.e. a delta draft
  distribution): accept draft ``d_j`` with probability
  ``min(1, p_j[d_j])``; at the first rejection, resample from the
  residual ``p_j`` with ``d_j`` zeroed out and renormalized; if every
  draft is accepted, the bonus token samples from ``p_K``. Each emitted
  position is marginally EXACTLY the target distribution — greedy
  degenerates to exact-match acceptance, making spec-on streams
  token-identical to the plain engine.

Draft state is invisible to every replay path: preemption recompute
(ISSUE 10), fleet failover (ISSUE 11) and prefix-cache attach (ISSUE 12)
all reconstruct from ``prompt + emitted tokens``, and rejected draft KV
is rollback-safe by construction (attention masks reads at ``<= ctx``;
later writes overwrite the garbage in place — see
``ops/paged_attention.py``'s verify-write notes).
"""

from __future__ import annotations

import numpy as np

__all__ = ["DraftSource", "NGramDraftSource", "SelfSpecDraftSource",
           "get_draft_source", "ngram_propose", "rejection_sample"]


# ---------------------------------------------------------------------------
# host-side rejection sampler (the numeric contract; the engine runs the
# same rule vectorized inside the compiled spec step — tests pin both)
# ---------------------------------------------------------------------------

def rejection_sample(probs, drafts, rng, greedy=False):
    """Verify point-mass drafts against target distributions.

    probs:  [K+1, V] float — target next-token distribution at each
            chunk position (position j conditions on the pending token
            plus drafts ``d_1..d_j``).
    drafts: [K] int — proposed tokens (a delta draft distribution).
    rng:    np.random.Generator (ignored under greedy).

    Returns ``(emitted, n_accepted)``: the emitted token list (always
    at least one token — the chain never leaves a step empty) and how
    many drafts were accepted. ``emitted[j] == drafts[j]`` for
    ``j < n_accepted``; the final entry is the rejection resample (or
    the bonus sample when every draft was accepted).

    Marginal exactness (the speculative-sampling theorem for q = delta):
    P(emit t at position j) = P(accept d_j) * 1[t == d_j]
    + P(reject) * residual_j(t) = min(1, p_j[d_j]) * 1[t == d_j]
    + (1 - p_j[d_j])_+ * (p_j(t) * 1[t != d_j]) / (1 - p_j[d_j])
    = p_j(t).
    """
    probs = np.asarray(probs, np.float64)
    drafts = [int(d) for d in drafts]
    k = len(drafts)
    assert probs.shape[0] >= k + 1
    emitted = []
    for j, d in enumerate(drafts):
        p = probs[j]
        if greedy:
            accept = d == int(np.argmax(p))
        else:
            accept = rng.random() < min(1.0, float(p[d]))
        if accept:
            emitted.append(d)
            continue
        # first rejection: resample from the renormalized residual
        if greedy:
            t = int(np.argmax(p))
        else:
            resid = p.copy()
            resid[d] = 0.0
            tot = resid.sum()
            if tot <= 0.0:           # p was a delta AT d yet u>=1 lost:
                t = d                # numerically impossible; stay exact
            else:
                t = int(rng.choice(len(resid), p=resid / tot))
        emitted.append(t)
        return emitted, j
    # every draft accepted: bonus token from the target at position K
    p = probs[k]
    if greedy:
        t = int(np.argmax(p))
    else:
        t = int(rng.choice(len(p), p=p / p.sum()))
    emitted.append(t)
    return emitted, k


# ---------------------------------------------------------------------------
# draft sources
# ---------------------------------------------------------------------------

class DraftSource:
    """Strategy seam: propose up to ``k`` draft tokens per drafting
    slot. ``propose`` sees the ENGINE (host token history, device
    mirrors) and returns host arrays — the engine clamps the counts to
    each slot's remaining budget and feeds the survivors into the spec
    step. Sources must be stateless across steps w.r.t. correctness:
    replay paths (preemption, failover, prefix attach) never see draft
    state."""

    name = "base"

    def propose(self, eng, slots, k):
        """-> (drafts [num_slots, k] int32, counts [num_slots] int32).

        ``slots`` lists the drafting slot indices; rows of other slots
        are ignored. ``counts[slot] <= k``; a 0 count degrades that
        slot to a plain length-1 decode inside the same spec step."""
        raise NotImplementedError


def ngram_propose(hist, k, max_n=3, min_n=1):
    """Prompt-lookup n-gram proposal: match the trailing ``n``-gram of
    ``hist`` (``prompt + emitted``, host ints) against every EARLIER
    window of the same history, longest n first, most recent match
    wins; propose the ``k`` tokens that followed the match. Returns an
    int32 array of length ``<= k`` (possibly empty)."""
    hist = np.asarray(hist, np.int32).reshape(-1)
    ln = hist.shape[0]
    for n in range(min(max_n, ln - 1), max(min_n, 1) - 1, -1):
        suffix = hist[ln - n:]
        # candidate windows hist[j:j+n] for j <= ln-n-1 — strictly
        # earlier than the suffix occurrence itself
        win = np.lib.stride_tricks.sliding_window_view(hist[:-1], n)
        hits = np.nonzero((win == suffix[None, :]).all(axis=1))[0]
        if hits.size == 0:
            continue
        j = int(hits[-1])
        prop = hist[j + n:j + n + k]
        if prop.size:
            return prop.astype(np.int32)
    return np.zeros((0,), np.int32)


class NGramDraftSource(DraftSource):
    """Prompt-lookup drafts (zero device work): the generated stream
    often repeats spans of its own prompt/history (code, quotes,
    templated text), so the continuation of the most recent matching
    n-gram is a cheap high-acceptance draft there — and a wrong draft
    costs only the already-paid ragged pass columns."""

    name = "ngram"

    def __init__(self, max_n=3, min_n=1):
        self.max_n = int(max_n)
        self.min_n = int(min_n)

    def propose(self, eng, slots, k):
        b = eng.num_slots
        drafts = np.zeros((b, k), np.int32)
        counts = np.zeros((b,), np.int32)
        for slot in slots:
            req = eng.slot_req[slot]
            if req is None:
                continue
            hist = np.concatenate(
                [np.asarray(req.prompt, np.int32),
                 np.asarray(req.tokens, np.int32)])
            prop = ngram_propose(hist, k, self.max_n, self.min_n)
            counts[slot] = prop.shape[0]
            drafts[slot, :prop.shape[0]] = prop
        return drafts, counts


class SelfSpecDraftSource(DraftSource):
    """Self-speculative skip-layer drafts: ONE compiled greedy K-step
    scan over the SAME weights with ``skip_layers`` decoder layers
    passed through (LayerSkip-style early-exit draft, PAPERS.md). The
    scan carries functionally-updated KV pools so draft token ``j+1``
    attends draft token ``j``'s KV — and then the updated pools are
    DISCARDED: the device-resident verified pools are never touched by
    drafting, which is what makes rejected drafts free to roll back.

    ``skip_layers`` accepts explicit layer indices or the default
    "skip the top half" (the standard self-speculation split: early
    layers carry most of the next-token signal)."""

    name = "self"

    def __init__(self, skip_layers=None):
        self._skip = tuple(sorted(skip_layers)) \
            if skip_layers is not None else None
        self._fns = {}          # (engine id, k) -> compiled scan

    def _skip_for(self, model):
        if self._skip is not None:
            return self._skip
        n = int(model.config.num_hidden_layers)
        return tuple(range((n + 1) // 2, n))

    def _draft_fn(self, eng, k):
        key = (id(eng), int(k))
        fn = self._fns.get(key)
        if fn is not None:
            return fn
        import jax
        import jax.numpy as jnp
        from ..framework.core import Tensor, no_grad, apply
        from ..jit import to_static
        model = eng.model
        skip = self._skip_for(model)

        def dstep(tok_t, ctx_t, tbl_t, mask_t, *pools):
            fwd = model.forward

            def fn(tok, ctx, tbl, mask, *pool_leaves):
                b = tok.shape[0]

                def body(carry, _):
                    tok_c, ctx_c, leaves = carry
                    with no_grad():
                        lgs, ncaches = fwd(
                            Tensor(tok_c.reshape(b, 1)),
                            caches=[Tensor(a) for a in leaves],
                            pos=Tensor(ctx_c[:, None]),
                            tables=(Tensor(tbl), Tensor(mask)),
                            skip_layers=skip)
                    lg = lgs[:, -1]._data.astype(jnp.float32)
                    nx = jnp.argmax(lg, -1).astype(jnp.int32)
                    nx = jnp.where(mask, nx, tok_c)
                    ctx_n = ctx_c + mask.astype(jnp.int32)
                    new_leaves = tuple(t._data for t in ncaches)
                    return (nx, ctx_n, new_leaves), nx

                carry0 = (tok, ctx, tuple(pool_leaves))
                _, toks = jax.lax.scan(body, carry0, jnp.arange(k))
                # [K, B] -> [B, K]; the carried pools die here — draft
                # KV is never returned to the engine
                return toks.T.astype(jnp.int32)

            return apply(fn, tok_t, ctx_t, tbl_t, mask_t, *pools,
                         n_outputs=1, differentiable=False,
                         name="spec_draft")

        fn = to_static(dstep)
        self._fns[key] = fn
        eng._compiled.add(("spec_draft", int(k)))
        return fn

    def propose(self, eng, slots, k):
        import jax.numpy as jnp
        from ..framework.core import Tensor
        b = eng.num_slots
        counts = np.zeros((b,), np.int32)
        if not slots or k <= 0:
            return np.zeros((b, max(k, 1)), np.int32)[:, :k], counts
        mask = np.zeros((b,), bool)
        mask[list(slots)] = True
        fn = self._draft_fn(eng, k)
        toks = fn(Tensor(eng._dev_tok), Tensor(eng._dev_ctx),
                  Tensor(eng._dev_tbl), Tensor(jnp.asarray(mask)),
                  *eng.pools)
        drafts = np.asarray(toks._data).astype(np.int32)
        counts[mask] = k
        return drafts, counts


def get_draft_source(spec):
    """Resolve a draft-source spec: a DraftSource instance passes
    through; the strings ``"ngram"`` and ``"self"`` build the default
    instances. (The tuner's ``spec_decode`` surface stores the
    string form.)"""
    if isinstance(spec, DraftSource):
        return spec
    if spec == "ngram":
        return NGramDraftSource()
    if spec in ("self", "skip_layer", "self_spec"):
        return SelfSpecDraftSource()
    raise ValueError(f"unknown draft source {spec!r} "
                     "(want 'ngram', 'self', or a DraftSource)")
