"""Process-backed fleet replica (ISSUE 16): the FleetReplica seam
over a REAL worker process.

:class:`ProcReplica` slots into :class:`~paddle_tpu.inference.fleet.ServingFleet`
(``replica_cls=ProcReplica``) speaking the
:mod:`~paddle_tpu.inference.wire` frame protocol to a spawned
``python -m paddle_tpu.inference.worker`` that owns the actual
:class:`~paddle_tpu.inference.serving.ContinuousBatchingEngine`. The
router — failover, hedging, breakers, exactly-once delivery,
token-identical greedy streams — is UNCHANGED: everything it touches
(``admit``/``step``/``salvage``/``load``/``health``) is served by a
parent-side SHADOW of the worker's state.

The shadow is the whole robustness story:

- **Salvage never needs the corpse.** Every ``step`` reply mirrors
  new tokens/hops into the parent-side :class:`ServedRequest` objects
  and re-states the worker's queue/slot occupancy, so when the worker
  dies, ``salvage_unfinished(shadow)`` returns complete idempotent
  replay payloads (prompt + every token already delivered) without
  asking the dead process anything.
- **Dead vs hung vs lossy.** ``waitpid``/EOF ⇒ *dead*: respawn under
  the PR-6 restart budget (exponential backoff + jitter) and replay
  the shadow; past budget the step raises and the PR-11 breaker
  opens. Missed heartbeats or an exhausted RPC deadline ⇒ *hung*:
  flight-recorder bundle, SIGTERM-with-grace then SIGKILL, and the
  replica reports itself wedged so the fleet ejects it via the
  HEALTH check, not the breaker. Truncated/garbage/duplicated frames
  ⇒ *lossy*: a typed ``WireError`` per incident, decoder resync, and
  a bounded retransmit (the worker's rpc-id reply cache makes
  retransmits exactly-once) — never a hang, never a half-applied
  message.
- **Observability survives the boundary.** Step replies piggyback a
  registry snapshot diff folded into a parent-side shadow registry —
  the SAME registry the fleet federates, so watermark banking (PR-13)
  keeps fleet totals dip-free across worker respawns — and worker
  hops merge into the one cross-replica timeline through a
  monotonic-clock offset handshake.
"""

from __future__ import annotations

import os
import random
import signal
import subprocess
import sys
import time

import numpy as np

from ..profiler import flight_recorder as _frec
from ..profiler import metrics as _pmetrics
from .fleet import FleetReplica
from .reliability import (AdmissionController, DeadlineExceeded,
                          Overloaded, ReplicaFailed, RequestCancelled,
                          RequestQuarantined, ServingError, record_hop)
from .serving import _StatsView
from .wire import (WireClosed, WireError, WireTimeout, WireTransport,
                   socketpair)

_pmetrics.declare("proc/spawns", "counter",
                  "worker processes launched (initial spawns + "
                  "respawns) by process-backed replicas")
_pmetrics.declare("proc/respawns", "counter",
                  "dead workers relaunched under the replica's "
                  "restart budget (shadow requests replayed)")
_pmetrics.declare("proc/heartbeat_misses", "counter",
                  "worker declared hung: heartbeat silence past "
                  "hb_timeout_s (SIGTERM-with-grace then SIGKILL, "
                  "flight-recorder bundle dumped)")
_pmetrics.declare("proc/rpc_retries", "counter",
                  "RPC retransmits after a deadline or a wire error "
                  "(exactly-once: the worker's reply cache dedupes)")
_pmetrics.declare("wire/errors", "counter",
                  "typed wire faults survived: corrupt, oversized, "
                  "out-of-order or garbage frames (decoder resynced)")
_pmetrics.declare("proc/worker_rss_bytes", "gauge",
                  "resident set size of the replica's worker process "
                  "(from its last step reply)")
_pmetrics.declare("proc/rpc_ms", "histogram",
                  "parent-observed RPC round-trip latency to the "
                  "worker, ms (bounded reservoir)")

#: typed-error reconstruction across the wire (worker sends the class
#: name; isinstance contracts must hold parent-side)
_ERROR_TYPES = {c.__name__: c for c in
                (ServingError, RequestCancelled, DeadlineExceeded,
                 RequestQuarantined, Overloaded, ReplicaFailed)}


def _rebuild_error(type_name, msg):
    cls = _ERROR_TYPES.get(type_name, ServingError)
    err = cls.__new__(cls)
    Exception.__init__(err, msg)
    return err


class _WorkerDied(Exception):
    """Internal: the worker process is gone (EOF / waitpid / fatal)."""


class _WorkerHung(Exception):
    """Internal: heartbeats stopped or the RPC hard deadline passed."""


class _ShadowEngine:
    """The parent-side mirror of the worker's engine: the surface the
    fleet router, the admission controller and ``salvage_unfinished``
    read. ``queue``/``slot_req`` hold the PARENT's ServedRequest
    objects (tokens mirrored on every harvest); geometry comes from
    the worker's init reply; ``metrics`` is a real registry the fleet
    federates."""

    def __init__(self, replica):
        self._replica = replica
        self._fleet_replica_id = replica.id
        self.metrics = _pmetrics.MetricsRegistry()
        self._stats = _StatsView(self.metrics)
        self.queue: list = []
        self.slot_req: list = []
        self.completed: list = []
        # geometry placeholders until the init reply lands
        self.num_slots = 1
        self.page_size = 0
        self.max_len = 0
        self.decode_chunk = 1
        self.num_pages = 2
        self._gauges: dict = {}

    def _adopt_geometry(self, g):
        self.num_slots = int(g["num_slots"])
        self.page_size = int(g["page_size"])
        self.max_len = int(g["max_len"])
        self.decode_chunk = int(g["decode_chunk"])
        self.num_pages = int(g["num_pages"])
        if not self.slot_req:
            self.slot_req = [None] * self.num_slots

    # -- router/admission surface --------------------------------------

    def _check_fits(self, prompt_len, max_new):
        self._replica._ready_for_admission()
        if prompt_len + max_new > self.max_len:
            raise ValueError(
                f"prompt ({prompt_len}) + max_new_tokens ({max_new}) "
                f"exceeds engine max_len {self.max_len}")
        need = -(-(prompt_len + max_new) // self.page_size)
        if need > self.num_pages - 1:
            raise ValueError(
                f"request needs {need} pages but the pool only has "
                f"{self.num_pages - 1} allocatable")

    def requeue(self, req):
        if req.finished:
            self.completed.append(req)
            return
        self._check_fits(req.prompt.size, req.max_new_tokens)
        self._replica._admit_rpc(req)   # raises before shadow mutates
        self.queue.append(req)

    def cancel(self, request_id):
        return self._replica._cancel_rpc(request_id)

    def handoff(self):
        return self._replica._handoff_rpc()

    def has_work(self):
        return bool(self.queue) or any(
            r is not None and not r.finished for r in self.slot_req)

    def gauges(self):
        return dict(self._gauges)

    def reset_gauges(self):
        try:
            self._replica._rpc_checked("reset_gauges", {})
        except _WorkerHung as e:
            self._replica._declare_hung(e)
        except _WorkerDied as e:
            self._replica._respawn_or_raise(e)
        for k in self._stats:
            self._stats[k] = 0
        self._gauges = {}


class _ProcSupervisor:
    """The supervisor-shaped face the fleet expects: ``engine`` is
    the shadow, ``restarts`` is the respawn count (the SAME budget
    semantics — checked before the counter, raises past it), and
    ``step()`` is one step RPC."""

    def __init__(self, replica):
        self._r = replica
        self.completed: list = []

    @property
    def engine(self):
        return self._r._shadow

    @property
    def restarts(self):
        return self._r.respawns

    @property
    def max_restarts(self):
        return self._r.max_restarts

    def cancel(self, request_id):
        return self._r._cancel_rpc(request_id)

    def gauges(self):
        return self._r._shadow.gauges()

    def has_work(self):
        return self._r._shadow.has_work()

    def step(self):
        return self._r._step_rpc()


class ProcReplica(FleetReplica):
    """A :class:`FleetReplica` whose engine lives in a worker process
    (module docstring). ``spec`` is the worker recipe::

        {"factory": "paddle_tpu.inference.worker:llama_engine",
         "kwargs": {...engine/model kwargs...}}

    A ``_spawn_fn`` entry (callable -> ``(proc, parent_socket)``)
    overrides process launch — the hermetic-test seam."""

    def __init__(self, replica_id, spec, *, max_restarts=2,
                 max_queue=64, default_ttft_slo_s=None,
                 min_retry_after_s=0.05,
                 rpc_deadline_s=1.0, rpc_hard_deadline_s=120.0,
                 init_deadline_s=300.0, rpc_retries=4,
                 hb_interval_s=0.2, hb_timeout_s=1.5,
                 wire_retries=4, term_grace_s=0.5,
                 respawn_backoff_s=0.02, respawn_backoff_cap_s=2.0,
                 respawn_jitter=0.25, seed=0):
        self.id = int(replica_id)
        self.spec = dict(spec)
        self.max_restarts = int(max_restarts)
        self.rpc_deadline_s = float(rpc_deadline_s)
        self.rpc_hard_deadline_s = float(rpc_hard_deadline_s)
        self.init_deadline_s = float(init_deadline_s)
        self.rpc_retries = int(rpc_retries)
        self.hb_interval_s = float(hb_interval_s)
        self.hb_timeout_s = float(hb_timeout_s)
        self.wire_retries = int(wire_retries)
        self.term_grace_s = float(term_grace_s)
        self.respawn_backoff_s = float(respawn_backoff_s)
        self.respawn_backoff_cap_s = float(respawn_backoff_cap_s)
        self.respawn_jitter = float(respawn_jitter)
        self._rng = random.Random(seed * 7919 + self.id)

        self._shadow = _ShadowEngine(self)
        self.supervisor = _ProcSupervisor(self)
        self.admission = AdmissionController(
            self._shadow, max_queue=max_queue,
            default_ttft_slo_s=default_ttft_slo_s,
            min_retry_after_s=min_retry_after_s)

        reg = self._shadow.metrics
        self._c_spawns = reg.counter("proc/spawns")
        self._c_respawns = reg.counter("proc/respawns")
        self._c_hb_misses = reg.counter("proc/heartbeat_misses")
        self._c_rpc_retries = reg.counter("proc/rpc_retries")
        self._c_wire_errors = reg.counter("wire/errors")
        self._g_rss = reg.gauge("proc/worker_rss_bytes")
        self._h_rpc = reg.histogram("proc/rpc_ms")

        # FleetReplica health-state surface (no super().__init__ —
        # the in-process supervisor/admission it builds are replaced
        # by the shadow-backed ones above)
        self.state = "ready"
        self.drain_deadline = None
        self.eject_kind = None
        self.last_beat = time.perf_counter()
        self.last_progress = self.last_beat
        self._idle_marker = None
        self._stale_turns = 0

        self.respawns = 0
        self._hung = False
        self._proc = None
        self._tr = None
        self._ready = False
        #: heartbeat liveness only applies once the worker has beaten
        #: at least once — interpreter boot + package import run long
        #: before the hb thread exists (process death still detected
        #: via waitpid; boot is bounded by the init hard deadline)
        self._saw_beat = False
        self._clock_offset = 0.0
        self._next_rpc = 0
        self._pending_init = None
        self._spawn()           # init RPC in flight; readiness lazy

    # ---- process lifecycle ---------------------------------------------

    @property
    def worker_pid(self):
        return self._proc.pid if self._proc is not None else None

    def _spawn(self):
        spawn_fn = self.spec.get("_spawn_fn")
        if spawn_fn is not None:
            self._proc, parent_sock = spawn_fn(self)
        else:
            parent_sock, child_sock = socketpair()
            env = dict(os.environ)
            import paddle_tpu
            pkg_root = os.path.dirname(
                os.path.dirname(os.path.abspath(paddle_tpu.__file__)))
            env["PYTHONPATH"] = pkg_root + os.pathsep \
                + env.get("PYTHONPATH", "")
            try:
                import jax
                plat = jax.config.jax_platforms
                if plat:
                    env.setdefault("JAX_PLATFORMS", plat)
                cache = jax.config.jax_compilation_cache_dir
                if cache:
                    env.setdefault("JAX_COMPILATION_CACHE_DIR", cache)
                if jax.config.jax_disable_most_optimizations:
                    env.setdefault("PADDLE_TPU_WORKER_DISOPT", "1")
            except Exception:  # noqa: BLE001 — env passthrough only
                pass
            child_fd = child_sock.fileno()
            self._proc = subprocess.Popen(
                [sys.executable, "-m", "paddle_tpu.inference.worker",
                 "--fd", str(child_fd),
                 "--hb-interval", str(self.hb_interval_s)],
                pass_fds=(child_fd,), env=env,
                stdout=subprocess.DEVNULL)
            child_sock.close()
        self._tr = WireTransport(parent_sock, replica_id=self.id,
                                 side="parent")
        self._ready = False
        self._saw_beat = False
        self._migrating = []     # rids parked worker-side (step reply)
        self._c_spawns.inc()
        self.last_beat = time.perf_counter()
        # fire the init without waiting: replicas spawned together
        # import/compile concurrently, readiness is drained on first use
        self._pending_init = self._send_rpc(
            "init", {"spec": {"factory": self.spec.get("factory"),
                              "kwargs": self.spec.get("kwargs", {})}})

    def _ensure_ready(self):
        if self._ready:
            return
        if self._pending_init is None:
            raise ReplicaFailed(self.id, "worker has no init in flight")
        reply = self._await_reply(self._pending_init,
                                  deadline_s=self.rpc_deadline_s,
                                  hard_s=self.init_deadline_s,
                                  payload=None, retransmit=False)
        self._pending_init = None
        self._shadow._adopt_geometry(reply["geom"])
        self._ready = True
        self._clock_sync()

    def _clock_sync(self):
        """Monotonic-clock offset handshake: 3 pings, keep the
        minimum-RTT sample; worker timestamps map into the parent's
        ``perf_counter`` domain as ``t_worker + offset``."""
        best = None
        for _ in range(3):
            t0 = time.perf_counter()
            reply = self._rpc_checked("clock", {})
            t1 = time.perf_counter()
            rtt = t1 - t0
            offset = (t0 + rtt / 2.0) - float(reply["t"])
            if best is None or rtt < best[0]:
                best = (rtt, offset)
        self._clock_offset = best[1]

    def _reap(self, kill=False):
        if self._proc is None:
            return
        try:
            if kill:
                self._proc.kill()
            self._proc.wait(timeout=5.0)
        except (OSError, subprocess.TimeoutExpired):
            pass
        if self._tr is not None:
            self._tr.close()

    def _declare_hung(self, cause):
        """The hung path: bundle, SIGTERM-with-grace, SIGKILL, and
        mark wedged so the fleet ejects via the HEALTH check (not the
        breaker) — SIGKILL also fells a SIGSTOPped process."""
        if self._hung:
            return
        self._c_hb_misses.inc()
        _frec.record_event("proc_worker_hung", replica=self.id,
                           pid=self.worker_pid, cause=str(cause)[:200])
        rec = _frec.get_recorder()
        if rec is not None:
            rec.dump(f"proc replica {self.id} worker hung: {cause}")
        try:
            self._proc.terminate()
            deadline = time.monotonic() + self.term_grace_s
            while time.monotonic() < deadline:
                if self._proc.poll() is not None:
                    break
                time.sleep(0.01)
        except OSError:
            pass
        self._reap(kill=True)
        self._hung = True

    def _respawn_or_raise(self, cause):
        """The dead path: salvage is ALREADY parent-side (the shadow);
        respawn under the restart budget with backoff + jitter and
        replay every unfinished shadow request; past budget, raise —
        the fleet opens the breaker and reroutes the same shadow."""
        _frec.record_event("proc_worker_dead", replica=self.id,
                           cause=str(cause)[:200],
                           respawns=self.respawns)
        self._reap(kill=True)
        # hoist the salvage set ONCE: a replay lap that dies partway
        # through re-admission must not shrink it to the requests it
        # managed to re-append — every lap (and the budget-spent
        # raise) carries the full unfinished set
        salvage = [r for r in self._shadow.queue
                   if not r.finished]
        salvage += [r for r in self._shadow.slot_req
                    if r is not None and not r.finished]
        salvage.sort(key=lambda r: r.request_id)
        while True:
            if self.respawns >= self.max_restarts:
                # leave the shadow holding the full set — the fleet's
                # breaker path salvages from it on eject
                self._shadow.queue = list(salvage)
                self._shadow.slot_req = [None] * max(
                    1, self._shadow.num_slots)
                raise ReplicaFailed(
                    self.id, f"worker respawn budget "
                    f"({self.max_restarts}) spent: {cause}")
            self.respawns += 1
            self._c_respawns.inc()
            back = min(self.respawn_backoff_cap_s,
                       self.respawn_backoff_s
                       * (2.0 ** (self.respawns - 1)))
            back *= 1.0 + self.respawn_jitter * self._rng.random()
            time.sleep(back)
            self._shadow.queue = []
            self._shadow.slot_req = [None] * max(
                1, self._shadow.num_slots)
            try:
                self._spawn()
                self._ensure_ready()
                for req in salvage:
                    record_hop(req, "respawn", replica=self.id,
                               tokens=len(req.tokens))
                    self._rpc_checked("admit",
                                      self._admit_payload(req))
                    self._shadow.queue.append(req)
            except (_WorkerDied, _WorkerHung, WireError) as e:
                cause = e
                continue
            return

    # ---- RPC engine ----------------------------------------------------

    def _send_rpc(self, op, payload):
        rpc_id = self._next_rpc
        self._next_rpc += 1
        msg = {"kind": "rpc", "id": rpc_id, "op": op}
        if payload:
            msg.update(payload)
        try:
            self._tr.send(msg)
        except WireClosed as e:
            raise _WorkerDied(e) from e
        self._pending = msg
        return rpc_id

    def _await_reply(self, rpc_id, *, deadline_s, hard_s, payload,
                     retransmit=True):
        """Drive recv until the reply for ``rpc_id`` lands. Heartbeat
        frames refresh liveness; their absence past ``hb_timeout_s``
        declares the worker hung. A quiet-but-alive worker gets
        bounded retransmits (a dropped frame is the only way an alive
        worker misses an RPC), then patience until the hard deadline
        (first-step XLA compiles run long under fresh heartbeats)."""
        t0 = time.perf_counter()
        t_send = t0
        attempts = 0
        wire_errs = 0
        deadline = t0 + deadline_s
        hard = t0 + hard_s
        while True:
            if self._proc is not None \
                    and self._proc.poll() is not None:
                raise _WorkerDied(
                    f"worker pid {self.worker_pid} exited "
                    f"rc={self._proc.returncode}")
            try:
                frame = self._tr.recv(0.02)
            except WireTimeout:
                frame = None
            except WireClosed as e:
                raise _WorkerDied(e) from e
            except WireError as e:
                self._c_wire_errors.inc()
                wire_errs += 1
                if wire_errs > self.wire_retries:
                    raise _WorkerDied(
                        f"wire unusable after {wire_errs} typed "
                        f"errors: {e}") from e
                if retransmit:
                    self._retransmit(payload)
                    attempts += 1
                continue
            now = time.perf_counter()
            if frame is not None:
                # ANY frame is liveness evidence: from the first one
                # on, heartbeat cadence applies (a worker that stops
                # beating mid-boot is bounded by the init hard
                # deadline instead)
                self.last_beat = now
                self._saw_beat = True
                kind = frame.get("kind")
                if kind == "hb":
                    continue
                if kind == "fatal":
                    etype = frame.get("etype")
                    msg = frame.get("msg", "")
                    if etype == "AssertionError":
                        # the page-accounting audit must NEVER be
                        # laundered into a respawn
                        raise AssertionError(
                            f"worker {self.id} audit: {msg}")
                    raise _WorkerDied(f"worker fatal {etype}: {msg}")
                if kind == "reply" and frame.get("id") == rpc_id:
                    self._h_rpc.observe((now - t_send) * 1e3)
                    return frame
                continue                     # stale reply: skip
            hb_age = now - self.last_beat
            if self._saw_beat and hb_age > self.hb_timeout_s:
                raise _WorkerHung(
                    f"no heartbeat for {hb_age:.2f}s")
            if now >= hard:
                raise _WorkerHung(
                    f"rpc past hard deadline {hard_s:.1f}s "
                    f"(heartbeats still arriving)")
            if now >= deadline and retransmit \
                    and attempts < self.rpc_retries:
                # exponential backoff + jitter on the retransmit
                # cadence (the PR-11 discipline)
                back = min(2.0, deadline_s * (2.0 ** attempts))
                back *= 1.0 + 0.25 * self._rng.random()
                self._retransmit(payload)
                attempts += 1
                t_send = now
                deadline = now + back

    def _retransmit(self, payload):
        if payload is None:
            return
        self._c_rpc_retries.inc()
        try:
            self._tr.send(payload)
        except WireClosed as e:
            raise _WorkerDied(e) from e

    def _rpc_checked(self, op, payload, *, deadline_s=None,
                     hard_s=None):
        """Send + await; raises the internal died/hung exceptions for
        the op-level wrappers to classify."""
        rpc_id = self._send_rpc(op, payload)
        msg = dict(self._pending)
        reply = self._await_reply(
            rpc_id,
            deadline_s=deadline_s or self.rpc_deadline_s,
            hard_s=hard_s or self.rpc_hard_deadline_s,
            payload=msg)
        return reply

    # ---- op wrappers (dead/hung classification per caller) -------------

    def _ready_for_admission(self):
        """``_ensure_ready`` with router-grade classification: hung ⇒
        typed :class:`Overloaded` (shed, retry a sibling), dead ⇒
        respawn under budget (:class:`ReplicaFailed` past it)."""
        try:
            self._ensure_ready()
        except _WorkerHung as e:
            self._declare_hung(e)
            raise Overloaded(
                f"replica {self.id} worker hung",
                self.admission.min_retry_after_s) from e
        except _WorkerDied as e:
            self._respawn_or_raise(e)

    @staticmethod
    def _admit_payload(req):
        age = max(0.0, time.perf_counter()
                  - (req.t_arrive or time.perf_counter()))
        return {"req": {
            "rid": int(req.request_id),
            "prompt": [int(t) for t in np.asarray(req.prompt).ravel()],
            "max_new": int(req.max_new_tokens),
            "eos": req.eos_token_id,
            "priority": int(req.priority),
            "ttft_deadline_s": req.ttft_deadline_s,
            "deadline_s": req.deadline_s,
            "tenant": req.tenant,
            "tokens": [int(t) for t in req.tokens],
            "preemptions": int(req.preemptions),
            "no_migrate": bool(getattr(req, "no_migrate", False)),
            "age_s": age}}

    def _admit_rpc(self, req):
        # bounded by the restart budget: every retry lap burned a
        # respawn (or raised), so this terminates
        for _ in range(self.max_restarts + 2):
            try:
                self._ensure_ready()
                self._rpc_checked("admit", self._admit_payload(req))
                return
            except _WorkerHung as e:
                self._declare_hung(e)
                raise Overloaded(
                    f"replica {self.id} worker hung during admit",
                    self.admission.min_retry_after_s) from e
            except _WorkerDied as e:
                # respawn (budget permitting) re-admits the SHADOW —
                # this request is not in it yet, so retry it after
                self._respawn_or_raise(e)
        raise ReplicaFailed(self.id, "admit could not land")

    def _step_rpc(self):
        try:
            self._ensure_ready()
            reply = self._rpc_checked("step", {})
        except _WorkerHung as e:
            self._declare_hung(e)
            return []              # wedged() now says so; fleet ejects
        except _WorkerDied as e:
            self._respawn_or_raise(e)   # raises past budget → breaker
            return []              # restart counts as progress
        return self._apply_step(reply)

    def _cancel_rpc(self, request_id):
        # mark the shadow first: cancellation must stick even if the
        # worker dies before acting on it (the respawn replay carries
        # the flag via the engine's requeue lifecycle check)
        for req in list(self._shadow.queue) + list(
                self._shadow.slot_req):
            if req is not None and req.request_id == request_id \
                    and not req.finished:
                req.cancelled = True
        try:
            reply = self._rpc_checked("cancel",
                                      {"rid": int(request_id)})
        except _WorkerHung as e:
            self._declare_hung(e)
            return True
        except _WorkerDied as e:
            self._respawn_or_raise(e)
            return True
        return bool(reply.get("cancelled"))

    def _handoff_rpc(self):
        try:
            self._rpc_checked("handoff", {})
        except _WorkerHung as e:
            self._declare_hung(e)
        except _WorkerDied:
            pass      # dead worker: the shadow IS the handoff payload
        out = [r for r in self._shadow.queue if not r.finished]
        out += [r for r in self._shadow.slot_req
                if r is not None and not r.finished]
        out.sort(key=lambda r: r.request_id)
        for r in out:
            r.preemptions += 1
        self._shadow.queue = []
        self._shadow.slot_req = [None] * max(1,
                                             self._shadow.num_slots)
        return out

    def audit(self):
        """Worker-side page-accounting audit (the chaos gate's
        survivor check): returns the worker's verdict dict."""
        try:
            self._ensure_ready()
            return self._rpc_checked("audit", {})
        except _WorkerHung as e:
            self._declare_hung(e)
            raise ReplicaFailed(self.id, f"hung during audit: {e}") \
                from e
        except _WorkerDied as e:
            self._respawn_or_raise(e)
            return self._rpc_checked("audit", {})

    # ---- step reply application (mirror-on-harvest) --------------------

    def _apply_step(self, reply):
        shadow = self._shadow
        by_id = {r.request_id: r for r in shadow.queue}
        for r in shadow.slot_req:
            if r is not None:
                by_id[r.request_id] = r
        finished = []
        off = self._clock_offset
        for u in reply.get("updates", ()):
            req = by_id.get(u.get("rid"))
            if req is None:
                continue
            req.tokens.extend(int(t) for t in u.get("toks", ()))
            req.preemptions = int(u.get("preemptions",
                                        req.preemptions))
            for h in u.get("hops", ()):
                h = dict(h)
                if isinstance(h.get("t"), (int, float)):
                    h["t"] = h["t"] + off
                self._append_hop(req, h)
            if u.get("t_first") and not req.t_first:
                req.t_first = float(u["t_first"]) + off
            if u.get("finished"):
                req.finished = True
                req.finish_reason = u.get("reason")
                req.t_done = float(u.get("t_done") or 0.0) + off \
                    if u.get("t_done") else time.perf_counter()
                err = u.get("error")
                if err:
                    req.error = _rebuild_error(err[0], err[1])
                finished.append(req)
        # re-state occupancy from the worker's truth — a request
        # parked for migration occupies NEITHER queue nor slot worker
        # side, but must stay in the shadow (a worker death between
        # parking and pickup salvages it to prompt replay)
        shadow.queue = [by_id[r] for r in reply.get("queue", ())
                        if r in by_id]
        self._migrating = list(reply.get("migrating", ()))
        for rid in self._migrating:
            req = by_id.get(rid)
            if req is not None and req not in shadow.queue:
                shadow.queue.append(req)
        slots = reply.get("slots")
        if slots is not None:
            shadow.slot_req = [
                by_id.get(r) if r is not None else None
                for r in slots]
            if len(shadow.slot_req) < shadow.num_slots:
                shadow.slot_req += [None] * (
                    shadow.num_slots - len(shadow.slot_req))
        # registry snapshot diff -> shadow registry (federation
        # watermarks bank respawn dips upstream)
        for name, v in reply.get("counters", {}).items():
            shadow.metrics.counter(name).set(v)
        for name, v in reply.get("gauges_m", {}).items():
            shadow.metrics.gauge(name).set(v)
        for name, d in reply.get("hists", {}).items():
            h = shadow.metrics.histogram(name)
            with h._lock:
                h.count = int(d.get("count", 0))
                h.sum = float(d.get("sum", 0.0))
                h.min = d.get("min")
                h.max = d.get("max")
                h._samples = [float(x) for x in
                              d.get("samples", ())][:h.capacity]
        g = reply.get("gauges")
        if g:
            shadow._gauges = g
        rss = reply.get("rss")
        if rss:
            self._g_rss.set(int(rss))
        return finished

    # ---- disaggregation seam (RPC-backed; see fleet.FleetReplica) ------

    def take_migrations(self):
        """Pop the worker's parked migrations: mirror each request's
        absolute token list into the shadow object, drop it from this
        replica's shadow occupancy (ownership is moving), and decode
        the KV payload to numpy form. A dead worker loses the payload
        but never the request — it stayed in the shadow through
        ``migrating`` re-statement, so the respawn replays it from its
        prompt (the payload was an optimization, not the record)."""
        from .disagg import kv_payload_from_wire
        # the last step reply said nothing is parked: skip the RPC
        # (the pump polls every fleet turn; this keeps the idle cost
        # zero and gives chaos tests a deterministic pickup window)
        if not self._ready or not getattr(self, "_migrating", None):
            return []
        self._migrating = []
        try:
            reply = self._rpc_checked("take_migrations", {})
        except _WorkerHung as e:
            self._declare_hung(e)
            return []
        except _WorkerDied as e:
            self._respawn_or_raise(e)
            return []
        shadow = self._shadow
        by_id = {r.request_id: r for r in shadow.queue}
        for r in shadow.slot_req:
            if r is not None:
                by_id[r.request_id] = r
        out = []
        for m in reply.get("migrations", ()):
            req = by_id.get(m.get("rid"))
            if req is None:
                continue         # already salvaged off this replica
            toks = [int(t) for t in m.get("tokens", ())]
            if len(toks) >= len(req.tokens):
                req.tokens[:] = toks
            if m.get("t_first") and not req.t_first:
                req.t_first = float(m["t_first"]) + self._clock_offset
            rid = req.request_id
            shadow.queue = [r for r in shadow.queue
                            if r.request_id != rid]
            shadow.slot_req = [
                None if (r is not None and r.request_id == rid) else r
                for r in shadow.slot_req]
            out.append((req, kv_payload_from_wire(m.get("payload")
                                                  or {})))
        return out

    def import_migration(self, req, payload):
        """Land a migrated request + its KV pages on this replica's
        worker. Raises on a dead/hung worker — the caller
        (:meth:`~.disagg.DisaggServingFleet._migrate_one`) degrades to
        plain prompt replay; a worker that actually applied the import
        before dying is harmless because the respawned engine simply
        never saw it (exactly-once is the fleet's attempt ledger)."""
        from .disagg import kv_payload_to_wire
        self._shadow._check_fits(req.prompt.size, req.max_new_tokens)
        body = self._admit_payload(req)
        body["payload"] = kv_payload_to_wire(payload)
        try:
            self._ensure_ready()
            reply = self._rpc_checked("kv_import", body)
        except _WorkerHung as e:
            self._declare_hung(e)
            raise ReplicaFailed(
                self.id, f"hung during kv_import: {e}") from e
        except _WorkerDied as e:
            self._respawn_or_raise(e)
            raise ReplicaFailed(
                self.id, "worker died during kv_import") from e
        self._shadow.queue.append(req)
        return reply.get("import")

    def release_exported(self, request_id):
        """Ack a completed migration: the source worker unpins the
        exported chain (its pages become ordinary prefix-cache
        residents). Best-effort — a dead source has no pins left."""
        try:
            self._ensure_ready()
            reply = self._rpc_checked("kv_release",
                                      {"rid": int(request_id)})
        except _WorkerHung as e:
            self._declare_hung(e)
            return False
        except _WorkerDied as e:
            self._respawn_or_raise(e)
            return False
        return bool(reply.get("released"))

    @staticmethod
    def _append_hop(req, hop):
        from .reliability import MAX_HOPS
        if len(req.hops) >= MAX_HOPS:
            req.hops_dropped += 1
            return
        req.hops.append(hop)

    # ---- health overrides ----------------------------------------------

    def wedged(self, no_progress_turns):
        return self._hung or super().wedged(no_progress_turns)

    # ---- teardown -------------------------------------------------------

    def on_eject(self, kind):
        """Fleet ejection hook: reap the corpse (dead), or the already
        SIGKILLed hung worker — salvage read the shadow, nothing is
        owed by the process."""
        self.close()

    def close(self):
        if self._proc is not None:
            try:
                if self._proc.poll() is None and self._ready \
                        and not self._hung:
                    try:
                        self._send_rpc("shutdown", {})
                    except (_WorkerDied, WireError):
                        pass
                self._proc.terminate()
                self._proc.wait(timeout=2.0)
            except (OSError, subprocess.TimeoutExpired):
                self._reap(kill=True)
        if self._tr is not None:
            self._tr.close()
