"""Replica worker process (ISSUE 16): ``python -m
paddle_tpu.inference.worker --fd N``.

Owns ONE real :class:`~paddle_tpu.inference.serving.ContinuousBatchingEngine`
and serves the parent's RPCs (init / clock / admit / step / cancel /
handoff / reset_gauges / audit / shutdown) over the
:mod:`~paddle_tpu.inference.wire` frame protocol on an inherited
socket fd. Design points, all in service of the parent's
dead-vs-hung-vs-lossy classification:

- **Heartbeats** — a daemon thread sends ``{"kind": "hb"}`` every
  ``hb_interval_s`` from the moment the transport is up, BEFORE the
  heavy imports and the first XLA compile, so a busy worker is never
  mistaken for a hung one and a SIGSTOPped worker goes silent within
  one interval.
- **Exactly-once RPCs** — replies are cached by rpc id (bounded);
  a retransmitted request (the parent's answer to a dropped frame)
  returns the cached reply without re-executing, so an ``admit`` or
  ``step`` can never be applied twice.
- **Incremental harvest** — every ``step`` reply carries only the
  NEW tokens/hops per request since the last report (the parent
  mirrors them into its shadow requests — the salvage-from-shadow
  guarantee), plus a registry snapshot diff the parent folds into its
  federated shadow registry.
- **Fail loudly** — an ``AssertionError`` (the page-accounting audit)
  or any engine-fatal exception sends one ``fatal`` frame and exits
  nonzero: the parent either re-raises the audit (never laundered
  into a respawn) or respawns under its budget.
"""

from __future__ import annotations

import argparse
import importlib
import os
import socket
import sys
import threading
import time

from .wire import WireClosed, WireError, WireTimeout, WireTransport

_REPLY_CACHE = 16


def llama_engine(model="tiny", num_hidden_layers=1, seed=0,
                 dtype=None, **engine_kw):
    """The standard worker engine factory (spec-addressable as
    ``paddle_tpu.inference.worker:llama_engine``): a freshly seeded
    tiny/named Llama and a ContinuousBatchingEngine around it. The
    same seed on every worker ⇒ identical weights ⇒ greedy streams
    are token-identical across replicas and respawns."""
    import paddle_tpu as paddle
    from paddle_tpu.models import LlamaConfig, LlamaForCausalLM
    from .serving import ContinuousBatchingEngine

    cfg = getattr(LlamaConfig, model)()
    cfg.tensor_parallel = False
    cfg.scan_layers = False
    if num_hidden_layers:
        cfg.num_hidden_layers = int(num_hidden_layers)
    paddle.seed(int(seed))
    m = LlamaForCausalLM(cfg)
    if dtype:
        m.to(dtype=dtype)
    m.eval()
    if "prompt_buckets" in engine_kw:
        engine_kw["prompt_buckets"] = tuple(
            engine_kw["prompt_buckets"])
    engine_kw.setdefault("greedy", True)
    return ContinuousBatchingEngine(m, **engine_kw)


def _resolve_factory(dotted):
    """``pkg.mod:attr`` (or ``pkg.mod.attr``) -> callable."""
    if ":" in dotted:
        mod, attr = dotted.split(":", 1)
    else:
        mod, attr = dotted.rsplit(".", 1)
    return getattr(importlib.import_module(mod), attr)


def _rss_bytes():
    try:
        with open("/proc/self/statm") as f:
            return int(f.read().split()[1]) * os.sysconf("SC_PAGESIZE")
    except (OSError, ValueError, IndexError):
        return 0


class Worker:
    def __init__(self, transport):
        self.tr = transport
        self.engine = None
        #: rid -> [tokens reported, hops reported]
        self._reported: dict[int, list] = {}
        #: bounded exactly-once reply cache: rpc id -> reply body
        self._replies: dict[int, dict] = {}
        self._reply_order: list[int] = []
        #: last counters/gauges snapshot sent (diff base)
        self._sent_counters: dict[str, float] = {}
        self._sent_hist_counts: dict[str, int] = {}

    # -- protocol loop -------------------------------------------------

    def serve(self):
        while True:
            try:
                msg = self.tr.recv(timeout_s=60.0)
            except WireTimeout:
                continue             # quiet parent; keep serving
            except (WireClosed, OSError):
                return               # parent gone: exit cleanly
            except WireError:
                continue             # corrupt inbound; decoder resynced
            if msg.get("kind") != "rpc":
                continue
            rid, op = msg.get("id"), msg.get("op")
            if rid in self._replies:
                self.tr.send({"kind": "reply", "id": rid,
                              **self._replies[rid]})
                continue
            try:
                body = self._handle(op, msg)
            except Exception as e:  # noqa: BLE001 — fatal by contract
                try:
                    self.tr.send({"kind": "fatal",
                                  "etype": type(e).__name__,
                                  "msg": str(e)[:500]})
                except WireError:
                    pass
                raise
            body["ok"] = True
            self._replies[rid] = body
            self._reply_order.append(rid)
            if len(self._reply_order) > _REPLY_CACHE:
                self._replies.pop(self._reply_order.pop(0), None)
            self.tr.send({"kind": "reply", "id": rid, **body})
            if op == "shutdown":
                return

    # -- ops -----------------------------------------------------------

    def _handle(self, op, msg):
        if op == "init":
            spec = msg["spec"]
            factory = _resolve_factory(spec["factory"])
            self.engine = factory(**spec.get("kwargs", {}))
            eng = self.engine
            return {"pid": os.getpid(),
                    "geom": {"num_slots": eng.num_slots,
                             "page_size": eng.page_size,
                             "max_len": eng.max_len,
                             "decode_chunk": eng.decode_chunk,
                             "num_pages": eng.num_pages}}
        if op == "clock":
            return {"t": time.perf_counter()}
        if op == "ping":
            return {}
        if op == "admit":
            return self._admit(msg["req"])
        if op == "step":
            return self._step()
        if op == "cancel":
            return {"cancelled": bool(
                self.engine.cancel(int(msg["rid"])))}
        if op == "handoff":
            reqs = self.engine.handoff()
            for r in reqs:
                self._reported.pop(r.request_id, None)
            return {"rids": [r.request_id for r in reqs]}
        if op == "take_migrations":
            return self._take_migrations()
        if op == "kv_import":
            return self._kv_import(msg["req"], msg.get("payload"))
        if op == "kv_release":
            return {"released": bool(
                self.engine.release_exported(int(msg["rid"])))}
        if op == "reset_gauges":
            self.engine.reset_gauges()
            # counters were reset in place: resend absolute values so
            # the parent's shadow follows (its federation watermark
            # banks the dip)
            self._sent_counters.clear()
            self._sent_hist_counts.clear()
            return {}
        if op == "audit":
            return self._audit()
        if op == "shutdown":
            return {}
        raise ValueError(f"unknown rpc op {op!r}")

    @staticmethod
    def _make_req(d):
        import numpy as np
        from .serving import ServedRequest
        req = ServedRequest(
            int(d["rid"]),
            np.asarray(d["prompt"], np.int32),
            int(d["max_new"]),
            d.get("eos"),
            priority=int(d.get("priority", 0)),
            ttft_deadline_s=d.get("ttft_deadline_s"),
            deadline_s=d.get("deadline_s"),
            tenant=d.get("tenant"))
        req.t_arrive = time.perf_counter() \
            - max(0.0, float(d.get("age_s", 0.0)))
        # replayed tokens (a respawn re-admission): the engine's
        # requeue path re-prefills prompt + emitted tokens through
        # recompute, continuing the stream exactly where it was
        req.tokens = [int(t) for t in d.get("tokens", [])]
        req.preemptions = int(d.get("preemptions", 0))
        req.no_migrate = bool(d.get("no_migrate", False))
        return req

    def _admit(self, d):
        req = self._make_req(d)
        self.engine.requeue(req)
        self._reported[req.request_id] = [len(req.tokens), 0]
        return {}

    def _take_migrations(self):
        """Pop parked (request, KV payload) pairs in wire form. The
        reply cache keeps this exactly-once under retransmits; the
        parent mirrors absolute token lists into its shadow before
        handing ownership to a decode replica."""
        from .disagg import kv_payload_to_wire
        out = []
        for req, payload in self.engine.take_migrations():
            self._reported.pop(req.request_id, None)
            out.append({"rid": req.request_id,
                        "tokens": [int(t) for t in req.tokens],
                        "t_first": req.t_first,
                        "preemptions": req.preemptions,
                        "payload": kv_payload_to_wire(payload)})
        return {"migrations": out}

    def _kv_import(self, d, wire_payload):
        """Admit a migrated request WITH its prefill KV: the engine
        seeds the pages into its prefix cache and requeues, so the
        attach is a full-length prefix hit (module docstring of
        :mod:`.disagg`)."""
        from .disagg import kv_payload_from_wire
        req = self._make_req(d)
        res = self.engine.import_migration(
            req, kv_payload_from_wire(wire_payload or {}))
        self._reported[req.request_id] = [len(req.tokens), 0]
        return {"import": res}

    def _step(self):
        eng = self.engine
        finished = eng.step()
        updates = []
        live = [r for r in eng.slot_req if r is not None]
        live += [r for r in eng.queue]
        # parked migrations still report (first token + migrate_out
        # hop mirror into the parent shadow BEFORE ownership moves)
        migrating = [req for req, _ in
                     getattr(eng, "migrations_out", ())]
        live += migrating
        for req in live + list(finished):
            rep = self._reported.setdefault(req.request_id, [0, 0])
            toks = req.tokens[rep[0]:]
            hops = req.hops[rep[1]:]
            if not (toks or hops or req.finished):
                continue
            rep[0] += len(toks)
            rep[1] += len(hops)
            u = {"rid": req.request_id, "toks": [int(t) for t in toks],
                 "hops": [self._json_hop(h) for h in hops],
                 "preemptions": req.preemptions}
            if req.t_first:
                u["t_first"] = req.t_first
            if req.finished:
                u["finished"] = True
                u["reason"] = req.finish_reason
                u["t_done"] = req.t_done or time.perf_counter()
                if req.error is not None:
                    u["error"] = [type(req.error).__name__,
                                  str(req.error)[:300]]
                self._reported.pop(req.request_id, None)
            updates.append(u)
        body = {"done": [r.request_id for r in finished],
                "updates": updates,
                "queue": [r.request_id for r in eng.queue],
                "slots": [r.request_id if r is not None else None
                          for r in eng.slot_req],
                "migrating": [r.request_id for r in migrating],
                "rss": _rss_bytes()}
        body.update(self._metrics_diff())
        return body

    @staticmethod
    def _json_hop(h):
        out = {}
        for k, v in h.items():
            if isinstance(v, (str, int, float, bool)) or v is None:
                out[k] = v
            else:
                out[k] = repr(v)[:120]
        return out

    def _metrics_diff(self):
        """Registry snapshot diff: counters/gauges whose value moved
        since the last report (absolute values — the parent SETs its
        shadow series; federation watermarks keep fleet totals
        monotonic), histograms re-shipped whole when their count
        moved (bounded by the reservoir capacity)."""
        from ..profiler.metrics import Counter, Gauge, Histogram
        reg = self.engine.metrics
        counters, gauges, hists = {}, {}, {}
        for name in reg.names():
            m = reg.get(name)
            if isinstance(m, Counter):
                v = m.value
                if self._sent_counters.get(name) != v:
                    self._sent_counters[name] = v
                    counters[name] = v
            elif isinstance(m, Histogram):
                if self._sent_hist_counts.get(name) != m.count:
                    self._sent_hist_counts[name] = m.count
                    hists[name] = {"count": m.count, "sum": m.sum,
                                   "min": m.min, "max": m.max,
                                   "samples": m.samples()}
            elif isinstance(m, Gauge):
                v = m.value
                key = "g:" + name
                if self._sent_counters.get(key) != v:
                    self._sent_counters[key] = v
                    gauges[name] = v
        out = {}
        if counters:
            out["counters"] = counters
        if gauges:
            out["gauges_m"] = gauges
        if hists:
            out["hists"] = hists
        out["gauges"] = {k: v for k, v in self.engine.gauges().items()
                         if isinstance(v, (int, float))}
        return out

    def _audit(self):
        """Page-accounting numbers for the parent's survivor audit
        (the chaos gate's zero-leak assertion, across the process
        boundary)."""
        eng = self.engine
        free = len(eng._free_pages)
        prefix = getattr(eng, "prefix_cache_pages", 0)
        clean = (free + prefix == eng.num_pages - 1
                 and not eng._deferred_free
                 and all(not p for p in eng.slot_pages)
                 and all(not s for s in eng.slot_shared))
        return {"clean": bool(clean), "free": free, "prefix": prefix,
                "num_pages": eng.num_pages}


def _heartbeat_loop(transport, interval_s, stop):
    while not stop.wait(interval_s):
        try:
            transport.send({"kind": "hb", "t": time.perf_counter()})
        except WireError:
            return


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--fd", type=int, required=True)
    ap.add_argument("--hb-interval", type=float, default=0.2)
    args = ap.parse_args(argv)

    # pin the backend BEFORE any jax backend init: the container's
    # sitecustomize may have set jax_platforms to the TPU tunnel via
    # jax.config (which beats the env var), and a worker must land on
    # the platform its parent chose
    import jax
    plat = os.environ.get("JAX_PLATFORMS")
    if plat:
        jax.config.update("jax_platforms", plat)
    cache = os.environ.get("JAX_COMPILATION_CACHE_DIR")
    if cache:
        jax.config.update("jax_compilation_cache_dir", cache)
        jax.config.update(
            "jax_persistent_cache_min_entry_size_bytes", -1)
    if os.environ.get("PADDLE_TPU_WORKER_DISOPT"):
        jax.config.update("jax_disable_most_optimizations", True)

    sock = socket.socket(fileno=args.fd)
    tr = WireTransport(sock, side="worker")
    stop = threading.Event()
    hb = threading.Thread(target=_heartbeat_loop,
                          args=(tr, args.hb_interval, stop),
                          name="worker-hb", daemon=True)
    hb.start()
    try:
        Worker(tr).serve()
    finally:
        stop.set()
        tr.close()
    return 0


if __name__ == "__main__":
    sys.exit(main())
