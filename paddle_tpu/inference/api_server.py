"""OpenAI-compatible async streaming HTTP front door (ISSUE 15).

The engine/fleet stack has admission control, deadlines, preemption,
failover, prefix caching and per-tenant SLO accounting (PRs 10-13) —
this module is how a request actually reaches it over the wire.
:class:`ApiServer` is a stdlib-only ``asyncio`` streams server (no new
deps, the same discipline as ``profiler/exposition.py``) exposing:

- ``POST /v1/completions`` and ``POST /v1/chat/completions`` —
  OpenAI-schema request/response; ``"stream": true`` returns SSE
  ``data:`` chunks (one delta per harvested token batch) with a
  terminal ``data: [DONE]``, non-streaming returns one JSON document;
- ``GET /v1/models`` — the single served model id;
- ``GET /healthz`` — liveness (503 once the pump thread has died);
- ``GET /statusz`` — live front-door sections (connections, streams,
  per-route latency) merged with the backend fleet's sections, all
  through the SAME guarded :func:`~..profiler.httpbase.
  evaluate_sections` path as the observability exposition.

Threading model — the engine is cooperative and NOT thread-safe, so
exactly one thread ("api-pump") owns every backend mutation: it drains
an inbox of submit/cancel jobs, calls ``backend.step()`` in a loop,
and after each turn diffs ``len(req.tokens)`` per live stream against
the high-water mark already published, pushing fresh tokens into that
stream's ``asyncio.Queue`` via ``loop.call_soon_threadsafe`` — tokens
stream as they are HARVESTED, not at completion. The asyncio loop
("api-http") owns sockets only. Handler coroutines submit work to the
pump through ``concurrent.futures.Future`` bridges and never touch
the engine directly.

Mapping onto the ``ServedRequest`` surface:

- body fields beat ``X-Tenant`` / ``X-Priority`` /
  ``X-TTFT-Deadline-Ms`` / ``X-Deadline-Ms`` headers; unknown/absent
  tenant maps to ``"default"``, priority is clamped into
  ``serving.PRIORITY_RANGE``, malformed deadlines are a structured
  400 (:func:`parse_request_options` — the unit-testable door);
- :class:`~.reliability.Overloaded` becomes HTTP 429 with a
  ``Retry-After`` header computed from ``retry_after_s``;
- typed per-request errors (``DeadlineExceeded``, ``RequestCancelled``,
  ``RequestQuarantined``, ``ReplicaFailed``) map to OpenAI-style error
  JSON (non-streaming, with the partial text kept) or a terminal SSE
  error event, both carrying the request's finish_reason;
- a client disconnect mid-stream invokes ``cancel()`` so the pages go
  back to the pool (the audit-clean contract);
- the fleet-minted trace id returns as an ``X-Trace-Id`` response
  header, and the request's hop timeline gains ``http_recv`` /
  ``first_byte`` / ``last_byte`` hops.

Non-streaming responses are materialized-before-send
(``Content-Length`` framing via ``profiler.httpbase``); SSE is the one
deliberately unframed path, but every individual event is materialized
before its first byte is written.

Front-door traffic is metered as the ``http/*`` family (requests,
streams, disconnects, bytes, per-route latency) on the backend fleet's
federated registry (or a server-private registry for a bare engine) —
docs/observability.md has the table.
"""

from __future__ import annotations

import asyncio
import concurrent.futures
import json
import math
import queue as _queuelib
import threading
import time

import numpy as np

from ..profiler import metrics as _metrics
from ..profiler.httpbase import (evaluate_sections, http1_head,
                                 http1_response)
from .reliability import Overloaded, record_hop
from .serving import PRIORITY_RANGE

__all__ = ["ApiServer", "ApiError", "parse_request_options",
           "default_tokenize", "default_detokenize"]

_metrics.declare("http/requests", "counter",
                 "HTTP requests received by the API front door "
                 "(route-labeled children per endpoint)")
_metrics.declare("http/streams", "counter",
                 "SSE completion streams opened (stream=true requests "
                 "that passed admission)")
_metrics.declare("http/disconnects", "counter",
                 "client disconnects observed mid-request; each one "
                 "invokes cancel() so the request's pages are "
                 "reclaimed")
_metrics.declare("http/bytes_sent", "counter",
                 "response bytes written by the API front door, SSE "
                 "frames included")
_metrics.declare("http/errors", "counter",
                 "error responses returned by the front door (4xx/5xx "
                 "documents + terminal SSE error events)")
_metrics.declare("http/connections", "gauge",
                 "currently open client connections on the API front "
                 "door")
_metrics.declare("http/route_latency_ms", "histogram",
                 "per-request wall ms from parsed request to last "
                 "byte written (route-labeled children; SSE streams "
                 "count their full stream duration)")


# ---- the HTTP mapping door (ISSUE-15 satellite: unit-testable) -----------

class ApiError(Exception):
    """A structured client-visible HTTP error: ``status`` plus an
    OpenAI-style ``{"error": {...}}`` body."""

    def __init__(self, status, message, etype="invalid_request_error",
                 **extra):
        super().__init__(message)
        self.status = int(status)
        self.etype = str(etype)
        self.extra = dict(extra)

    def body(self) -> dict:
        err = {"message": str(self), "type": self.etype,
               "code": self.status}
        err.update(self.extra)
        return {"error": err}


def default_tokenize(text):
    """The dependency-free default tokenizer: the prompt string is
    whitespace-separated integer token ids (``"12 7 4983"``) — the
    shape the load harness and tests speak. Anything else is a 400
    (bring a real tokenizer via ``ApiServer(tokenize=...)``)."""
    toks = []
    for part in str(text).split():
        if not part.isdigit():
            raise ApiError(
                400, "the default tokenizer accepts whitespace-"
                     f"separated integer token ids; got {part!r} "
                     "(pass token-id lists, or construct ApiServer "
                     "with a real tokenize/detokenize pair)")
        toks.append(int(part))
    return toks


def default_detokenize(token_ids):
    """Inverse of :func:`default_tokenize`: space-joined ids. Streamed
    greedy content through this pair is byte-comparable with a direct
    engine run's token list."""
    return " ".join(str(int(t)) for t in token_ids)


def _pick(body, headers, body_key, header_key):
    """Body field beats header; returns (value, source) or (None, None)."""
    if body_key in body:
        return body[body_key], f"body.{body_key}"
    if header_key in headers:
        return headers[header_key], f"header {header_key}"
    return None, None


def parse_request_options(body, headers, priority_range=PRIORITY_RANGE):
    """Map request body fields + ``X-*`` headers onto the
    ``ServedRequest`` submit surface. Returns ``{tenant, priority,
    ttft_deadline_s, deadline_s}``; raises :class:`ApiError` (400,
    structured body) on malformed values.

    The contract (pinned by tests/test_api_server.py):

    - unknown/absent/non-string tenant -> ``"default"``;
    - priority must parse as an integer and is CLAMPED into
      ``priority_range`` (an untrusted client cannot out-rank the
      whole pool by sending 2**31);
    - deadlines arrive in MILLISECONDS (``ttft_deadline_ms`` /
      ``deadline_ms`` body fields, ``X-TTFT-Deadline-Ms`` /
      ``X-Deadline-Ms`` headers) and must be positive finite numbers.
    """
    headers = {str(k).lower(): v for k, v in dict(headers or {}).items()}
    body = dict(body or {})

    tenant, _src = _pick(body, headers, "tenant", "x-tenant")
    if not isinstance(tenant, str) or not tenant.strip():
        tenant = "default"
    else:
        tenant = tenant.strip()

    raw, src = _pick(body, headers, "priority", "x-priority")
    priority = 0
    if raw is not None:
        if isinstance(raw, bool) or not isinstance(raw, (int, str)):
            raise ApiError(400, f"priority must be an integer "
                                f"({src} = {raw!r})")
        try:
            priority = int(str(raw).strip())
        except ValueError:
            raise ApiError(400, f"priority must be an integer "
                                f"({src} = {raw!r})") from None
        lo, hi = priority_range
        priority = max(int(lo), min(int(hi), priority))

    def deadline_s(body_key, header_key):
        raw, src = _pick(body, headers, body_key, header_key)
        if raw is None:
            return None
        try:
            v = float(raw) if not isinstance(raw, bool) else math.nan
        except (TypeError, ValueError):
            v = math.nan
        if not math.isfinite(v) or v <= 0:
            raise ApiError(
                400, f"{body_key} must be a positive finite number of "
                     f"milliseconds ({src} = {raw!r})")
        return v / 1e3

    return {"tenant": tenant, "priority": priority,
            "ttft_deadline_s": deadline_s("ttft_deadline_ms",
                                          "x-ttft-deadline-ms"),
            "deadline_s": deadline_s("deadline_ms", "x-deadline-ms")}


#: typed per-request failure -> (HTTP status, OpenAI-style error type).
#: finish_reason comes from the request itself ("cancelled",
#: "deadline", "quarantined", "failed"); "eos" renders as OpenAI's
#: "stop". 499 is the nginx client-closed-request convention.
_ERROR_STATUS = {
    "RequestCancelled": (499, "cancelled"),
    "DeadlineExceeded": (504, "deadline_exceeded"),
    "RequestQuarantined": (500, "quarantined"),
    "ReplicaFailed": (502, "replica_failed"),
}


def _finish_reason(req) -> str | None:
    fr = getattr(req, "finish_reason", None)
    return "stop" if fr == "eos" else fr


# ---- backend adapters ----------------------------------------------------

class _FleetBackend:
    """A ServingFleet: fleet-global ids, federated registry, statusz
    sections, fleet-minted trace ids."""

    kind = "fleet"

    def __init__(self, fleet):
        self.fleet = fleet
        self.registry = fleet.metrics

    def submit(self, prompt_ids, max_new_tokens, **kw):
        return self.fleet.submit(prompt_ids, max_new_tokens, **kw)

    def step(self):
        return self.fleet.step()

    def has_work(self):
        return self.fleet.has_work()

    def cancel(self, rid):
        return self.fleet.cancel(rid)

    def live(self, rid):
        return self.fleet.request(rid)

    def track(self, rid, req):
        """Per-turn token view: attempts can be REPLACED mid-flight
        (failover carry, hedging), so the fleet re-resolves by id
        every turn — a dict lookup, not a scan."""
        return None

    def statusz_sections(self):
        return self.fleet.statusz_sections()


class _EngineBackend:
    """A bare ContinuousBatchingEngine or an EngineSupervisor (both
    expose add_request/step/cancel/request/has_work), optionally
    fronted by an AdmissionController for the 429 shed path."""

    kind = "engine"

    def __init__(self, engine, admission=None):
        self.engine = engine
        self.admission = admission
        self.registry = None       # server-private registry

    def submit(self, prompt_ids, max_new_tokens, **kw):
        if self.admission is not None:
            return self.admission.submit(prompt_ids, max_new_tokens,
                                         **kw)
        return self.engine.add_request(prompt_ids, max_new_tokens, **kw)

    def step(self):
        return self.engine.step()

    def has_work(self):
        return self.engine.has_work()

    def cancel(self, rid):
        return self.engine.cancel(rid)

    def live(self, rid):
        return self.engine.request(rid)

    def track(self, rid, req):
        """The engine mutates ONE ServedRequest object end to end
        (salvage/requeue adopt the same object), so the pump can read
        ``req.tokens`` directly instead of paying engine.request()'s
        completed-list scan per stream per turn."""
        return req

    def statusz_sections(self):
        return {}


def _make_backend(backend, admission=None):
    if hasattr(backend, "replicas") and hasattr(backend, "submit"):
        return _FleetBackend(backend)
    if hasattr(backend, "add_request"):
        return _EngineBackend(backend, admission)
    if hasattr(backend, "engine") and hasattr(backend, "submit"):
        # an AdmissionController passed directly
        return _EngineBackend(backend.engine, backend)
    raise TypeError(f"unsupported backend {type(backend).__name__}: "
                    "expected ContinuousBatchingEngine, "
                    "EngineSupervisor, AdmissionController or "
                    "ServingFleet")


class _Stream:
    """Pump-side view of one in-flight HTTP request: the id, the
    token high-water mark already published, the asyncio queue the
    handler coroutine drains, and (engine backends) the tracked
    ServedRequest object read directly per turn."""

    __slots__ = ("rid", "sent", "queue", "loop", "req")

    def __init__(self, rid, q, loop, req=None):
        self.rid = rid
        self.sent = 0
        self.queue = q
        self.loop = loop
        self.req = req

    def push(self, item):
        try:
            self.loop.call_soon_threadsafe(self.queue.put_nowait, item)
        except RuntimeError:
            pass    # loop already closed (server stopping)


def _deliver_batch(batch):
    """Loop-thread callback: fan one pump turn's items out to their
    stream queues (see ApiServer._publish)."""
    for q, item in batch:
        q.put_nowait(item)


# ---- the server ----------------------------------------------------------

class ApiServer:
    """The front door (module docstring). ``backend`` is a
    ``ContinuousBatchingEngine``, ``EngineSupervisor``,
    ``AdmissionController`` or ``ServingFleet``; ``port=0`` binds an
    ephemeral port (``server.port`` / ``server.url`` after
    :meth:`start`). ``tokenize``/``detokenize`` default to the
    integer-token-id codec (:func:`default_tokenize`)."""

    def __init__(self, backend, host="127.0.0.1", port=0,
                 model_id="paddle-tpu", tokenize=None, detokenize=None,
                 admission=None, registry=None,
                 priority_range=PRIORITY_RANGE, stream_chunk_tokens=1):
        self._backend = _make_backend(backend, admission)
        self.host = host
        self._port_req = int(port)
        self.port = None
        self.model_id = str(model_id)
        self.tokenize = tokenize or default_tokenize
        self.detokenize = detokenize or default_detokenize
        self.priority_range = tuple(priority_range)
        #: SSE throughput/latency dial: a stream's FIRST tokens and
        #: its final flush always publish immediately (TTFT and
        #: completion are never delayed), but mid-stream tokens wait
        #: until this many are pending before riding a chunk. >1
        #: trades inter-token latency for fewer json+write cycles —
        #: what saturated single-core serving wants.
        self.stream_chunk_tokens = max(1, int(stream_chunk_tokens))
        self.metrics = (registry or self._backend.registry
                        or _metrics.MetricsRegistry())

        self._loop = None
        self._server = None
        self._loop_thread = None
        self._pump_thread = None
        self._started = threading.Event()
        self._stop = threading.Event()
        self._wake = threading.Event()
        self._inbox = _queuelib.SimpleQueue()
        self._lock = threading.Lock()
        self._streams: dict = {}        # rid -> _Stream
        self._connections = 0
        self._routes_seen: set = set()
        self._pump_error = None

    # -- lifecycle ---------------------------------------------------------

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}"

    def start(self):
        if self._loop_thread is not None:
            return self
        self._loop = asyncio.new_event_loop()
        self._loop_thread = threading.Thread(
            target=self._run_loop, name="api-http", daemon=True)
        self._loop_thread.start()
        if not self._started.wait(timeout=10.0):
            raise RuntimeError("ApiServer failed to bind within 10s")
        if self.port is None:
            raise RuntimeError("ApiServer failed to bind "
                               f"{self.host}:{self._port_req}")
        self._pump_thread = threading.Thread(
            target=self._pump, name="api-pump", daemon=True)
        self._pump_thread.start()
        return self

    def stop(self):
        self._stop.set()
        self._wake.set()
        if self._pump_thread is not None:
            self._pump_thread.join(timeout=10.0)
            self._pump_thread = None
        if self._loop is not None and self._shutdown is not None:
            try:
                self._loop.call_soon_threadsafe(self._shutdown.set)
            except RuntimeError:
                pass
        if self._loop_thread is not None:
            self._loop_thread.join(timeout=10.0)
            self._loop_thread = None

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()
        return False

    _shutdown = None

    def _run_loop(self):
        asyncio.set_event_loop(self._loop)
        try:
            self._loop.run_until_complete(self._serve_main())
        except Exception:   # noqa: BLE001 — bind failure: start() sees
            pass            # port None and raises with context
        finally:
            self._started.set()
            try:
                self._loop.close()
            except RuntimeError:
                pass

    async def _serve_main(self):
        self._shutdown = asyncio.Event()
        self._server = await asyncio.start_server(
            self._handle_conn, self.host, self._port_req)
        self.port = self._server.sockets[0].getsockname()[1]
        self._started.set()
        await self._shutdown.wait()
        self._server.close()
        await self._server.wait_closed()
        # cancel lingering per-connection tasks so the loop can close
        for task in asyncio.all_tasks():
            if task is not asyncio.current_task():
                task.cancel()

    # -- the pump thread (owns every backend mutation) ---------------------

    def _pump(self):
        while not self._stop.is_set():
            progressed = self._drain_inbox()
            if self._backend.has_work():
                progressed = True
                try:
                    done = self._backend.step()
                except BaseException as exc:  # noqa: BLE001 — the
                    # backend died below its own containment (restart
                    # budget spent, audit assertion, ...): every live
                    # stream gets a terminal typed error instead of a
                    # silent hang, and /healthz goes 503
                    self._pump_error = exc
                    self._fail_streams(exc)
                    if isinstance(exc, (KeyboardInterrupt, SystemExit)):
                        raise
                    continue
                self._publish(done)
            if not progressed:
                self._wake.wait(0.005)
                self._wake.clear()
        # drain any last-moment jobs so their futures never hang
        self._drain_inbox()

    def _drain_inbox(self) -> bool:
        ran = False
        while True:
            try:
                fn, fut = self._inbox.get_nowait()
            except _queuelib.Empty:
                return ran
            ran = True
            if not fut.set_running_or_notify_cancel():
                continue
            try:
                fut.set_result(fn())
            except BaseException as exc:  # noqa: BLE001 — delivered
                fut.set_exception(exc)    # to the awaiting handler

    def _publish(self, done):
        """After one backend turn: push freshly harvested tokens to
        each live stream, and completion markers for delivered
        requests (tokens first — the delivered object is the
        authoritative final view). All of one turn's pushes ride a
        SINGLE call_soon_threadsafe: each wakeup makes the loop
        thread runnable mid-step and the GIL ping-pong starves the
        backend, so one loop wakeup per turn, not one per stream."""
        donemap = {r.request_id: r for r in (done or [])}
        with self._lock:
            streams = list(self._streams.items())
        batch = []
        for rid, st in streams:
            fin = donemap.get(rid)
            req = fin if fin is not None else \
                (st.req if st.req is not None
                 else self._backend.live(rid))
            if req is not None:
                toks = req.tokens
                pending = len(toks) - st.sent
                if pending > 0 and (fin is not None or st.sent == 0
                                    or pending
                                    >= self.stream_chunk_tokens):
                    fresh = [int(t) for t in toks[st.sent:]]
                    st.sent = len(toks)
                    batch.append((st.queue, ("tokens", fresh)))
            if fin is not None:
                with self._lock:
                    self._streams.pop(rid, None)
                batch.append((st.queue, ("done", fin)))
        if batch:
            try:
                self._loop.call_soon_threadsafe(_deliver_batch, batch)
            except RuntimeError:
                pass    # loop already closed (server stopping)

    def _fail_streams(self, exc):
        with self._lock:
            streams = list(self._streams.values())
            self._streams.clear()
        for st in streams:
            st.push(("fail", exc))

    async def _in_pump(self, fn):
        """Run ``fn`` on the pump thread (between backend turns) and
        await its result."""
        fut = concurrent.futures.Future()
        self._inbox.put((fn, fut))
        self._wake.set()
        return await asyncio.wrap_future(fut)

    # -- connection handling ----------------------------------------------

    async def _handle_conn(self, reader, writer):
        self._connections += 1
        self.metrics.gauge("http/connections").set(self._connections)
        try:
            parsed = await self._read_request(reader)
            if parsed is not None:
                await self._dispatch(parsed, reader, writer)
        except (ConnectionResetError, BrokenPipeError,
                asyncio.IncompleteReadError):
            pass
        except asyncio.CancelledError:
            raise
        except ApiError as exc:
            # a request-line/framing error surfaced before _dispatch
            self.metrics.counter("http/errors").inc()
            await self._try_write(writer, http1_response(
                exc.status, json.dumps(exc.body()),
                "application/json"))
        except Exception as exc:  # noqa: BLE001 — a handler bug must
            # answer 500, never drop the connection mid-parse
            self.metrics.counter("http/errors").inc()
            await self._try_write(writer, http1_response(
                500, json.dumps({"error": {
                    "message": f"{type(exc).__name__}: {exc}",
                    "type": "internal_error", "code": 500}}),
                "application/json"))
        finally:
            self._connections -= 1
            self.metrics.gauge("http/connections").set(
                self._connections)
            try:
                writer.close()
            except Exception:  # noqa: BLE001
                pass

    async def _read_request(self, reader):
        line = await reader.readline()
        if not line:
            return None
        parts = line.decode("latin-1", "replace").split()
        if len(parts) < 2:
            raise ApiError(400, f"malformed request line {line!r}")
        method, target = parts[0].upper(), parts[1]
        headers = {}
        while True:
            hl = await reader.readline()
            if hl in (b"\r\n", b"\n", b""):
                break
            name, _, value = hl.decode("latin-1", "replace").partition(":")
            headers[name.strip().lower()] = value.strip()
        body = b""
        try:
            n = int(headers.get("content-length", "0") or "0")
        except ValueError:
            n = 0
        if n > 0:
            body = await reader.readexactly(n)
        return method, target.split("?", 1)[0], headers, body

    async def _dispatch(self, parsed, reader, writer):
        method, path, headers, body = parsed
        route = path if path in ("/v1/completions",
                                 "/v1/chat/completions", "/v1/models",
                                 "/healthz", "/statusz") else "other"
        self._routes_seen.add(route)
        ctr = self.metrics.counter("http/requests")
        ctr.inc()                        # all-routes total (statusz)
        ctr.labels(route=route).inc()    # per-route series (/metrics)
        t0 = time.perf_counter()
        try:
            if path in ("/v1/completions", "/v1/chat/completions"):
                if method != "POST":
                    raise ApiError(405, f"{path} requires POST",
                                   etype="method_not_allowed")
                await self._completion(
                    path, headers, body, reader, writer,
                    chat=path.endswith("/chat/completions"))
            elif path == "/v1/models" and method == "GET":
                await self._try_write(writer, http1_response(
                    200, json.dumps({
                        "object": "list",
                        "data": [{"id": self.model_id,
                                  "object": "model",
                                  "owned_by": "paddle_tpu"}]}),
                    "application/json"))
            elif path == "/healthz" and method == "GET":
                if self._pump_error is not None:
                    self.metrics.counter("http/errors").inc()
                    await self._try_write(writer, http1_response(
                        503, json.dumps({"error": {
                            "message": f"pump dead: "
                                       f"{self._pump_error}",
                            "type": "unavailable", "code": 503}}),
                        "application/json"))
                else:
                    await self._try_write(writer, http1_response(
                        200, "ok\n", "text/plain; charset=utf-8"))
            elif path == "/statusz" and method == "GET":
                doc = evaluate_sections(self._statusz_sections())
                await self._try_write(writer, http1_response(
                    200, json.dumps(doc, default=str, sort_keys=True),
                    "application/json"))
            else:
                raise ApiError(404, f"unknown path {path!r}",
                               etype="not_found",
                               paths=["/v1/completions",
                                      "/v1/chat/completions",
                                      "/v1/models", "/healthz",
                                      "/statusz"])
        except ApiError as exc:
            self.metrics.counter("http/errors").inc()
            extra = []
            if exc.status == 429 and "retry_after_s" in exc.extra:
                extra = [("Retry-After", str(int(math.ceil(
                    exc.extra["retry_after_s"]))))]
            await self._try_write(writer, http1_response(
                exc.status, json.dumps(exc.body()),
                "application/json", extra))
        finally:
            ms = (time.perf_counter() - t0) * 1e3
            self.metrics.histogram("http/route_latency_ms") \
                .labels(route=route).observe(ms)

    async def _try_write(self, writer, data: bytes):
        writer.write(data)
        # only pay the drain() round-trip when the transport actually
        # built up backpressure — per-chunk drains dominate the SSE
        # hot path otherwise
        if writer.transport.get_write_buffer_size() > 65536:
            await writer.drain()
        self.metrics.counter("http/bytes_sent").inc(len(data))

    # -- /statusz sections -------------------------------------------------

    def _statusz_sections(self):
        sections = dict(self._backend.statusz_sections())

        def http_section():
            snap = {}
            for name in ("http/requests", "http/streams",
                         "http/disconnects", "http/bytes_sent",
                         "http/errors"):
                m = self.metrics.get(name)
                snap[name.split("/", 1)[1]] = \
                    0 if m is None else m.value
            snap["connections"] = self._connections
            with self._lock:
                snap["live_streams"] = len(self._streams)
            snap["pump_alive"] = self._pump_error is None
            return snap

        def routes_section():
            out = {}
            hist = self.metrics.get("http/route_latency_ms")
            if hist is None:
                return out
            for route in sorted(self._routes_seen):
                child = hist.labels(route=route)
                out[route] = {
                    "count": child.count,
                    "p50_ms": round(child.percentile(50), 3),
                    "p99_ms": round(child.percentile(99), 3)}
            return out

        sections["http"] = http_section
        sections["routes"] = routes_section
        return sections

    # -- completions -------------------------------------------------------

    def _prompt_ids(self, body, chat):
        if chat:
            msgs = body.get("messages")
            if not isinstance(msgs, list) or not msgs:
                raise ApiError(400, "chat completions require a "
                                    "non-empty messages list")
            ids = []
            for m in msgs:
                if not isinstance(m, dict) or "content" not in m:
                    raise ApiError(400, "each message must be an "
                                        "object with a content field")
                ids.extend(self.tokenize(str(m["content"])))
            if not ids:
                raise ApiError(400, "messages tokenized to an empty "
                                    "prompt")
            return ids
        prompt = body.get("prompt")
        if isinstance(prompt, str):
            ids = self.tokenize(prompt)
        elif isinstance(prompt, list) and prompt and \
                all(isinstance(t, int) and not isinstance(t, bool)
                    for t in prompt):
            ids = [int(t) for t in prompt]
        else:
            raise ApiError(400, "prompt must be a non-empty string or "
                                "a list of integer token ids")
        if not ids:
            raise ApiError(400, "prompt tokenized to an empty "
                                "sequence")
        return ids

    async def _completion(self, path, headers, body_bytes, reader,
                          writer, chat):
        try:
            body = json.loads(body_bytes.decode("utf-8")) \
                if body_bytes else {}
        except (ValueError, UnicodeDecodeError):
            raise ApiError(400, "request body is not valid JSON") \
                from None
        if not isinstance(body, dict):
            raise ApiError(400, "request body must be a JSON object")
        opts = parse_request_options(body, headers,
                                     self.priority_range)
        prompt_ids = self._prompt_ids(body, chat)
        max_tokens = body.get("max_tokens", 16)
        if not isinstance(max_tokens, int) or isinstance(max_tokens,
                                                         bool) \
                or max_tokens < 1:
            raise ApiError(400, "max_tokens must be a positive "
                                "integer")
        eos = body.get("eos_token_id")
        if eos is not None and (not isinstance(eos, int)
                                or isinstance(eos, bool)):
            raise ApiError(400, "eos_token_id must be an integer")
        stream = bool(body.get("stream", False))

        prompt_arr = np.asarray(prompt_ids, dtype=np.int32)
        q = asyncio.Queue()

        def _do_submit():
            rid = self._backend.submit(
                prompt_arr, max_tokens, eos_token_id=eos, **opts)
            req = self._backend.live(rid)
            if req is not None:
                record_hop(req, "http_recv", route=path)
            st = _Stream(rid, q, self._loop,
                         req=self._backend.track(rid, req))
            with self._lock:
                self._streams[rid] = st
            return rid, req

        try:
            rid, req0 = await self._in_pump(_do_submit)
        except Overloaded as exc:
            raise ApiError(
                429, str(exc), etype="overloaded",
                retry_after_s=round(exc.retry_after_s, 4)) from None
        except ValueError as exc:
            # _check_fits: prompt/max_new beyond the pool geometry
            raise ApiError(400, str(exc)) from None

        trace_id = getattr(req0, "trace_id", None)
        trace_id = rid if trace_id is None else trace_id
        if stream:
            await self._stream_response(path, chat, rid, req0, q,
                                        trace_id, prompt_ids, reader,
                                        writer)
        else:
            await self._unary_response(chat, rid, req0, q, trace_id,
                                       prompt_ids, reader, writer)

    def _cancel_for_disconnect(self, rid):
        self.metrics.counter("http/disconnects").inc()
        with self._lock:
            self._streams.pop(rid, None)
        # cancel on the pump thread; fire-and-forget (the client is
        # gone — nobody is waiting on the result)
        self._inbox.put((lambda: self._backend.cancel(rid),
                         concurrent.futures.Future()))
        self._wake.set()

    async def _await_outcome(self, rid, q, reader, on_tokens=None):
        """Drain the stream queue until a terminal item, watching the
        client socket for disconnect (EOF/reset -> cancel() so pages
        are reclaimed). Returns ("done", req) | ("fail", exc) |
        ("disconnect", None)."""
        watcher = asyncio.create_task(reader.read(65536))
        try:
            while True:
                getter = asyncio.create_task(q.get())
                await asyncio.wait({getter, watcher},
                                   return_when=asyncio.FIRST_COMPLETED)
                if not getter.done():
                    # client hung up (or sent junk mid-stream) before
                    # the backend finished
                    getter.cancel()
                    self._cancel_for_disconnect(rid)
                    return "disconnect", None
                kind, payload = getter.result()
                if kind == "tokens":
                    toks = list(payload)
                    # coalesce every batch already sitting in the
                    # queue into ONE SSE chunk: when the pump outruns
                    # the writer (single-core CPU, slow client) this
                    # collapses many small json+write cycles into one
                    # without delaying any token that could have been
                    # sent sooner
                    tail = None
                    while tail is None:
                        try:
                            k2, p2 = q.get_nowait()
                        except asyncio.QueueEmpty:
                            break
                        if k2 == "tokens":
                            toks.extend(p2)
                        else:
                            tail = (k2, p2)
                    if on_tokens is not None:
                        ok = await on_tokens(toks)
                        if not ok:
                            self._cancel_for_disconnect(rid)
                            return "disconnect", None
                    if tail is not None:
                        return tail
                    continue
                return kind, payload
        finally:
            watcher.cancel()

    # -- non-streaming -----------------------------------------------------

    async def _unary_response(self, chat, rid, req0, q, trace_id,
                              prompt_ids, reader, writer):
        kind, payload = await self._await_outcome(rid, q, reader)
        if kind == "disconnect":
            return
        if kind == "fail":
            self.metrics.counter("http/errors").inc()
            await self._try_write(writer, http1_response(
                500, json.dumps({"error": {
                    "message": f"backend failed: {payload}",
                    "type": "internal_error", "code": 500,
                    "trace_id": trace_id}}),
                "application/json",
                [("X-Trace-Id", str(trace_id))]))
            return
        req = payload
        text = self.detokenize(req.tokens)
        created = int(time.time())
        extra = [("X-Trace-Id", str(trace_id))]
        if req.error is not None:
            status, etype = _ERROR_STATUS.get(
                type(req.error).__name__, (500, "serving_error"))
            self.metrics.counter("http/errors").inc()
            doc = {"error": {"message": str(req.error), "type": etype,
                             "code": status,
                             "finish_reason": _finish_reason(req),
                             "trace_id": trace_id,
                             # a failed stream still delivers its
                             # partial prefix, never silence
                             "partial_text": text}}
            await self._try_write(writer, http1_response(
                status, json.dumps(doc), "application/json", extra))
            record_hop(req, "last_byte")
            return
        if chat:
            choice = {"index": 0,
                      "message": {"role": "assistant",
                                  "content": text},
                      "finish_reason": _finish_reason(req)}
            obj, oid = "chat.completion", f"chatcmpl-{trace_id}"
        else:
            choice = {"index": 0, "text": text,
                      "finish_reason": _finish_reason(req)}
            obj, oid = "text_completion", f"cmpl-{trace_id}"
        doc = {"id": oid, "object": obj, "created": created,
               "model": self.model_id, "choices": [choice],
               "usage": {"prompt_tokens": len(prompt_ids),
                         "completion_tokens": len(req.tokens),
                         "total_tokens": len(prompt_ids)
                         + len(req.tokens)}}
        record_hop(req, "first_byte")
        await self._try_write(writer, http1_response(
            200, json.dumps(doc), "application/json", extra))
        record_hop(req, "last_byte")

    # -- SSE streaming -----------------------------------------------------

    def _sse_chunk(self, chat, oid, created, *, delta_text=None,
                   finish_reason=None, role=False, error=None):
        if chat:
            delta = {}
            if role:
                delta["role"] = "assistant"
            if delta_text is not None:
                delta["content"] = delta_text
            choice = {"index": 0, "delta": delta,
                      "finish_reason": finish_reason}
            obj = "chat.completion.chunk"
        else:
            choice = {"index": 0, "text": delta_text or "",
                      "finish_reason": finish_reason}
            obj = "text_completion"
        doc = {"id": oid, "object": obj, "created": created,
               "model": self.model_id, "choices": [choice]}
        if error is not None:
            doc["error"] = error
        return b"data: " + json.dumps(doc).encode("utf-8") + b"\n\n"

    async def _stream_response(self, path, chat, rid, req0, q,
                               trace_id, prompt_ids, reader, writer):
        self.metrics.counter("http/streams").inc()
        created = int(time.time())
        oid = (f"chatcmpl-{trace_id}" if chat else f"cmpl-{trace_id}")
        head = http1_head(200, [
            ("Content-Type", "text/event-stream; charset=utf-8"),
            ("Cache-Control", "no-cache"),
            ("Connection", "close"),
            ("X-Trace-Id", str(trace_id))])
        try:
            await self._try_write(writer, head)
        except (ConnectionResetError, BrokenPipeError, OSError):
            self._cancel_for_disconnect(rid)
            return

        state = {"first": True, "tokens": [], "text": ""}

        async def on_tokens(fresh):
            state["tokens"].extend(fresh)
            full = self.detokenize(state["tokens"])
            delta, state["text"] = full[len(state["text"]):], full
            chunk = self._sse_chunk(chat, oid, created,
                                    delta_text=delta,
                                    role=state["first"])
            try:
                await self._try_write(writer, chunk)
            except (ConnectionResetError, BrokenPipeError, OSError):
                return False
            if state["first"]:
                state["first"] = False
                live = req0 if req0 is not None \
                    else self._backend.live(rid)
                if live is not None:
                    record_hop(live, "first_byte")
            return True

        kind, payload = await self._await_outcome(rid, q, reader,
                                                  on_tokens)
        if kind == "disconnect":
            return
        if kind == "fail":
            self.metrics.counter("http/errors").inc()
            err = {"message": f"backend failed: {payload}",
                   "type": "internal_error", "code": 500,
                   "trace_id": trace_id}
            await self._try_write(writer, self._sse_chunk(
                chat, oid, created, finish_reason="failed",
                error=err))
            await self._try_write(writer, b"data: [DONE]\n\n")
            return
        req = payload
        # the delivered object is authoritative: any tokens the pump
        # attached to the terminal item's request beyond what we
        # streamed were already pushed as a tokens item before "done"
        error = None
        if req.error is not None:
            status, etype = _ERROR_STATUS.get(
                type(req.error).__name__, (500, "serving_error"))
            self.metrics.counter("http/errors").inc()
            error = {"message": str(req.error), "type": etype,
                     "code": status, "trace_id": trace_id}
        final = self._sse_chunk(chat, oid, created,
                                finish_reason=_finish_reason(req),
                                error=error)
        try:
            await self._try_write(writer, final)
            await self._try_write(writer, b"data: [DONE]\n\n")
        except (ConnectionResetError, BrokenPipeError, OSError):
            self.metrics.counter("http/disconnects").inc()
            return
        record_hop(req, "last_byte")
