"""Serving reliability layer: typed failures, SLO-aware admission
control, and supervised engine recovery (ISSUE 10).

The ``ContinuousBatchingEngine`` handles overload INSIDE the pool
(preemption + recompute, deadlines, cancellation, step-failure
containment — serving.py); this module is what stands in FRONT of and
AROUND it:

- **Typed errors** — a request never just disappears: it finishes with
  tokens, or with :class:`RequestCancelled`, :class:`DeadlineExceeded`
  or :class:`RequestQuarantined` attached to ``ServedRequest.error``;
  a submission the system cannot absorb raises :class:`Overloaded`
  with a computed ``retry_after_s``.
- :class:`AdmissionController` — a bounded admission queue that sheds
  load AT THE DOOR when the queue is full or when the engine's
  ``serving/ttft_ms`` / ``serving/itl_ms`` histograms (the PR-9
  observability plane) predict the request would miss its TTFT
  deadline anyway. Accepted requests keep their SLOs; excess load gets
  a typed rejection and a retry-after instead of a doomed queue slot.
- :class:`EngineSupervisor` — the containment ESCAPE hatch: when the
  engine dies anyway (watchdog stall ``RuntimeError``, a containment-
  budget escape, a crash below the step boundary), the supervisor
  dumps a flight-recorder bundle, tears the engine down, re-queues
  every queued + in-flight request into a fresh engine (idempotent
  replay from prompt + already-emitted tokens — the same recompute
  path preemption uses) and retries with a bounded restart budget
  (the PR-6 elastic-launcher pattern, in-process).

Deliberately engine-agnostic: nothing here imports serving.py, so the
two modules cannot cycle; the controller and supervisor duck-type the
engine surface (``queue``/``slot_req``/``gauges``/``requeue``/...).
"""

from __future__ import annotations

import time
from collections import deque

from ..profiler import flight_recorder as _frec
from ..profiler import metrics as _metrics

__all__ = ["ServingError", "RequestCancelled", "DeadlineExceeded",
           "RequestQuarantined", "Overloaded", "ReplicaFailed",
           "AdmissionController", "EngineSupervisor",
           "salvage_unfinished", "record_hop", "MAX_HOPS"]

_metrics.declare("restart/engine_restarts", "counter",
                 "supervised serving-engine teardown+restart cycles "
                 "(EngineSupervisor)")
_metrics.declare("restart/engine_requeued", "counter",
                 "queued + in-flight requests salvaged into a fresh "
                 "engine at a supervised restart (idempotent replay "
                 "from prompt + emitted tokens)")


# ---- typed failures --------------------------------------------------------

class ServingError(RuntimeError):
    """Base of every typed serving failure; ``request_id`` is set for
    per-request errors (None for :class:`Overloaded`)."""

    request_id: int | None = None


class RequestCancelled(ServingError):
    """The request's ``cancel()`` was honored: pages freed, tokens
    already emitted kept on the request."""

    def __init__(self, request_id):
        super().__init__(f"request {request_id} cancelled")
        self.request_id = request_id


class DeadlineExceeded(ServingError):
    """A TTFT or total deadline expired — queued, mid-prefill or
    mid-decode. ``kind`` is ``"ttft"`` or ``"total"``."""

    def __init__(self, request_id, kind, deadline_s):
        super().__init__(
            f"request {request_id} missed its {kind} deadline "
            f"({deadline_s}s)")
        self.request_id = request_id
        self.kind = kind
        self.deadline_s = deadline_s


class RequestQuarantined(ServingError):
    """The request rode ``max_strikes`` failed compiled steps and was
    isolated by the containment boundary (the poison-request shape)."""

    def __init__(self, request_id, cause=""):
        super().__init__(
            f"request {request_id} quarantined after repeated step "
            f"failures" + (f": {cause}" if cause else ""))
        self.request_id = request_id
        self.cause = cause


class Overloaded(ServingError):
    """Admission-control rejection: the system is shedding load.
    ``retry_after_s`` is the controller's estimate of when a retry has
    a fighting chance. The fleet router (ISSUE 11) propagates the MAX
    of this value across every replica that shed, and its own retries
    honor it as a backoff floor."""

    def __init__(self, reason, retry_after_s):
        super().__init__(
            f"overloaded: {reason} (retry after "
            f"{retry_after_s:.3f}s)")
        self.retry_after_s = float(retry_after_s)


class ReplicaFailed(ServingError):
    """The request's serving replica died (or the whole fleet became
    unavailable) and the fleet's bounded retry budget was spent.
    Tokens already emitted are kept on the request — a failed stream
    delivers its partial prefix plus this typed error, never
    silence."""

    def __init__(self, request_id, cause=""):
        super().__init__(
            f"request {request_id} abandoned after replica failure"
            + (f": {cause}" if cause else ""))
        self.request_id = request_id
        self.cause = cause


#: per-request hop bound (ISSUE 13): lifecycle events are few, but a
#: preemption storm replaying one victim hundreds of times must not
#: grow its trace without limit — past the bound, hops are counted,
#: not stored. The helper lives HERE (engine-agnostic, stdlib-only)
#: because serving.py imports this module; serving re-exports both.
MAX_HOPS = 64


def record_hop(req, kind, replica=None, **fields):
    """Append one hop to a request's cross-replica trace. Duck-typed:
    requests without a ``hops`` list are silently skipped. A few dict
    stores — cheap enough for the hot path (the engine call sites ride
    the ``obs_overhead_frac`` window).

    Past ``MAX_HOPS`` the LIST's last slot becomes a ``truncated``
    marker counting the overflow — in the list itself, because hedge
    copies are distinct request objects sharing ONE list: a per-object
    counter on the attempt that happened to hit the cap would be
    invisible in the delivered winner's trace summary."""
    hops = getattr(req, "hops", None)
    if hops is None:
        return
    if len(hops) >= MAX_HOPS:
        req.hops_dropped += 1
        last = hops[-1]
        if last.get("kind") == "truncated":
            last["dropped"] += 1
        else:
            # dropped=2: the displaced final hop AND the current one
            hops[-1] = {"kind": "truncated",
                        "t": time.perf_counter(), "dropped": 2}
        return
    h = {"kind": kind, "t": time.perf_counter()}
    if replica is not None:
        h["replica"] = replica
    if fields:
        h.update(fields)
    hops.append(h)


def salvage_unfinished(engine):
    """Every queued + in-flight request of an engine being torn down
    or ejected, in arrival order — the idempotent-replay set (prompt +
    tokens already emitted) a fresh engine or a sibling replica
    re-queues through the recompute path. Read-only: safe on a dead
    engine whose device state is no longer trustworthy (only host-side
    containers are touched). Shared by :class:`EngineSupervisor`
    restarts and the :class:`~paddle_tpu.inference.fleet.ServingFleet`
    breaker/ejection path, so the salvage contract cannot fork."""
    salvage = [r for r in engine.queue if not r.finished]
    salvage += [r for r in engine.slot_req
                if r is not None and not r.finished]
    # disaggregation (ISSUE 17): requests migrated OUT of a slot but
    # not yet picked up by the router live in neither container — a
    # prefill engine dying mid-transfer must still salvage them (the
    # KV payload is lost with the engine; prompt replay is the
    # fallback, exactly like any preemption)
    salvage += [req for req, _ in getattr(engine, "migrations_out", ())
                if not req.finished]
    salvage.sort(key=lambda r: r.request_id)
    return salvage


# ---- SLO-aware admission control -------------------------------------------

class AdmissionController:
    """Bounded admission queue + SLO predictor in front of an engine
    (or an :class:`EngineSupervisor` — anything exposing ``.engine`` or
    being one).

    Shedding policy, checked at :meth:`submit` time:

    1. **Queue bound** — more than ``max_queue`` requests waiting means
       every further accept just manufactures a deadline miss; reject
       with a retry-after derived from the queue's estimated drain
       time.
    2. **SLO prediction** — with latency history available (the
       engine's ``serving/ttft_ms`` / ``serving/itl_ms`` bounded
       reservoirs), predicted TTFT = ttft_p99 + queued-work drain time;
       a request whose TTFT deadline (or the controller's
       ``default_ttft_slo_s``) is below the prediction is shed
       immediately — it would occupy pages only to time out.

    Cold engines (no completed request yet) admit on the queue bound
    alone: there is nothing to predict from.
    """

    def __init__(self, target, max_queue=64, default_ttft_slo_s=None,
                 min_retry_after_s=0.05, shed_window_s=10.0):
        self._target = target
        self.max_queue = int(max_queue)
        self.default_ttft_slo_s = default_ttft_slo_s
        self.min_retry_after_s = float(min_retry_after_s)
        self.accepted = 0
        self.shed = 0
        #: recent shed instants (bounded): the windowed shed RATE the
        #: autoscaler and the fleet gauges read — the counter above is
        #: lifetime-monotonic and says nothing about "now"
        self.shed_window_s = float(shed_window_s)
        self._shed_times = deque(maxlen=1024)

    @property
    def engine(self):
        return getattr(self._target, "engine", self._target)

    # -- prediction --------------------------------------------------------

    def _rates(self, eng):
        """(ttft_p99_s, itl_p50_s) from the engine's latency
        reservoirs — read through the PUBLIC per-engine metrics
        registry (``engine.metrics``), not serving.py internals — or
        None while there is no history."""
        h_ttft = eng.metrics.get("serving/ttft_ms")
        h_itl = eng.metrics.get("serving/itl_ms")
        if h_ttft is None or h_ttft.count == 0:
            return None
        itl = (h_itl.percentile(50) / 1e3) \
            if h_itl is not None and h_itl.count else 0.0
        return h_ttft.percentile(99) / 1e3, itl

    def _queued_drain_s(self, eng, itl_s):
        """Estimated seconds to drain the CURRENT queue: remaining
        tokens across queued requests, served at the observed
        per-token latency across num_slots lanes."""
        queued_tok = sum(r.max_new_tokens - len(r.tokens)
                         for r in eng.queue)
        return queued_tok * itl_s / max(1, eng.num_slots)

    def predicted_ttft_s(self):
        """The controller's TTFT prediction for a request submitted
        NOW (None while the engine has no latency history)."""
        eng = self.engine
        rates = self._rates(eng)
        if rates is None:
            return None
        ttft_p99, itl = rates
        return ttft_p99 + self._queued_drain_s(eng, itl)

    def _retry_after_s(self, eng):
        rates = self._rates(eng)
        if rates is None:
            return self.min_retry_after_s
        _, itl = rates
        # time for the queue to drain below half the bound — the point
        # where a retry stops being a coin flip
        excess = max(0, len(eng.queue) - self.max_queue // 2)
        per_req = itl * (
            sum(r.max_new_tokens for r in eng.queue)
            / max(1, len(eng.queue))) / max(1, eng.num_slots)
        return max(self.min_retry_after_s, excess * per_req)

    def retry_after_s(self):
        """The controller's CURRENT retry-after estimate, without
        shedding anything — the fleet router reads this to compute the
        fleet-wide ``Overloaded.retry_after_s`` (max across sheddable
        replicas) instead of inventing a constant."""
        return self._retry_after_s(self.engine)

    def shed_rate(self, now=None):
        """Sheds per second over the trailing ``shed_window_s`` — the
        live pressure signal (ISSUE 19): the ``shed`` counter is
        monotonic and cannot distinguish an overload NOW from one an
        hour ago. Prunes as it reads, so an idle controller decays to
        0.0 without any writer."""
        now = time.perf_counter() if now is None else now
        horizon = now - self.shed_window_s
        while self._shed_times and self._shed_times[0] < horizon:
            self._shed_times.popleft()
        return len(self._shed_times) / self.shed_window_s

    # -- the door ----------------------------------------------------------

    def _shed(self, eng, reason, floor_s=0.0):
        """``floor_s``: a shed-specific lower bound — an SLO-
        prediction shed must tell the client to wait at least the
        prediction OVERSHOOT (queue-drain math alone reads ~0 while
        the queue is below half the bound, inviting an immediate
        re-shed loop)."""
        retry = max(self._retry_after_s(eng), floor_s)
        self.shed += 1
        self._shed_times.append(time.perf_counter())
        eng.metrics.counter("serving/shed_rejections").inc()
        eng.metrics.gauge("serving/shed_retry_after_s").set(retry)
        _frec.record_event("shed", reason=reason,
                           queued=len(eng.queue),
                           retry_after_s=round(retry, 4))
        raise Overloaded(reason, retry)

    def _gate(self, eng, ttft_deadline_s):
        """The shed decision shared by :meth:`submit` and
        :meth:`admit` — queue bound first, then the SLO prediction."""
        if len(eng.queue) >= self.max_queue:
            self._shed(eng, f"admission queue full "
                            f"({len(eng.queue)}/{self.max_queue})")
        slo = ttft_deadline_s if ttft_deadline_s is not None \
            else self.default_ttft_slo_s
        if slo is not None:
            pred = self.predicted_ttft_s()
            if pred is not None and pred > slo:
                self._shed(eng, f"predicted TTFT {pred:.3f}s exceeds "
                                f"deadline {slo:.3f}s",
                           floor_s=pred - slo)

    def submit(self, prompt_ids, max_new_tokens, eos_token_id=None,
               priority=0, ttft_deadline_s=None,
               deadline_s=None, tenant=None) -> int:
        """Admit or shed. Returns the request id; raises
        :class:`Overloaded` (with ``retry_after_s``) when the queue is
        full or the SLO predictor says the deadline is already lost."""
        eng = self.engine
        self._gate(eng, ttft_deadline_s)
        rid = eng.add_request(prompt_ids, max_new_tokens,
                              eos_token_id=eos_token_id,
                              priority=priority,
                              ttft_deadline_s=ttft_deadline_s,
                              deadline_s=deadline_s, tenant=tenant)
        self.accepted += 1   # after validation — a rejected oversize
        return rid           # submission must not count as accepted

    def admit(self, req) -> int:
        """Router-side admission (ISSUE 11): the same shed policy as
        :meth:`submit`, applied to a PRE-BUILT ``ServedRequest`` — the
        fleet mints fleet-global ids and failover replays arrive
        carrying already-emitted tokens, so the engine adopts the
        object through its ``requeue()`` recompute path instead of
        minting a fresh one."""
        eng = self.engine
        self._gate(eng, req.ttft_deadline_s)
        eng.requeue(req)     # validates fit; raises before accounting
        self.accepted += 1
        return req.request_id


# ---- supervised recovery ---------------------------------------------------

class EngineSupervisor:
    """Bounded-restart supervision around a serving engine.

    ``engine_factory`` builds a fresh engine (same model/geometry);
    the first one is built eagerly as ``self.engine``. :meth:`run`
    drives it to completion; when the engine dies — the stall
    ``RuntimeError``, a containment-budget escape, any crash below the
    step boundary — or returns with a slot it could never drain (a
    wedged stream), the supervisor:

    1. dumps a flight-recorder bundle (post-mortem),
    2. salvages every queued + in-flight request,
    3. builds a fresh engine and re-queues them (idempotent replay:
       prompt + tokens already emitted re-prefill through the
       recompute path, so delivered prefixes are never re-served),
    4. retries, at most ``max_restarts`` times — then the original
       failure propagates (the PR-6 restart-budget contract: bounded,
       never infinite).
    """

    def __init__(self, engine_factory, max_restarts=2):
        self._factory = engine_factory
        self.engine = engine_factory()
        self.max_restarts = int(max_restarts)
        self.restarts = 0
        self.completed: list = []
        self._returned: set[int] = set()   # id()s already handed back
        # monotonic counters salvaged from torn-down engines, so
        # gauges() reports the whole supervised lifetime, not just the
        # engine that happens to be alive (bench reads these)
        self._carried: dict = {}

    # engine pass-throughs (the supervisor IS the serving surface)
    def add_request(self, *a, **kw):
        return self.engine.add_request(*a, **kw)

    def cancel(self, request_id):
        return self.engine.cancel(request_id)

    def request(self, request_id):
        return self.engine.request(request_id)

    #: gauges() keys that are monotonic counters — summable across the
    #: engines a supervised lifetime burns through
    _COUNTER_GAUGES = (
        "preempt_evictions", "preempt_recompute_tokens",
        "requests_cancelled", "deadline_expired", "shed_rejections",
        "quarantined", "containments", "tokens_emitted", "prefills",
        "requests_completed", "chunks_dispatched", "unified_steps",
        "prefix_cache_hits", "prefix_cache_misses",
        "prefix_cache_tokens_saved", "prefix_cache_evictions",
        "prefix_cache_cow_forks")

    def gauges(self):
        """The live engine's gauges, with monotonic counters summed
        over every engine this supervisor has torn down — restart must
        not zero the lifetime economics."""
        g = dict(self.engine.gauges())
        for k, v in self._carried.items():
            g[k] = g.get(k, 0) + v
        # derived ratios must agree with the summed counters they
        # summarize (the live engine's local ratio contradicts the
        # carried hits/misses after a restart)
        if "prefix_cache_hit_rate" in g:
            tot = g.get("prefix_cache_hits", 0) \
                + g.get("prefix_cache_misses", 0)
            g["prefix_cache_hit_rate"] = \
                g.get("prefix_cache_hits", 0) / tot if tot else 0.0
        return g

    def has_work(self):
        return self.engine.has_work() \
            or any(r is not None for r in self.engine.slot_req)

    def step(self):
        """One supervised scheduler turn — the ServingFleet's driver
        unit (ISSUE 11): the cooperative fleet loop round-robins
        replicas, so each replica advances one ``engine.step()`` at a
        time under the SAME restart contract as :meth:`run`. A step
        failure that escapes the engine's containment boundary tears
        the engine down, salvages queue + in-flight into a fresh one
        and returns nothing this turn; past ``max_restarts`` the
        failure propagates (the fleet opens the replica's circuit
        breaker). Returns the requests completed by this turn, each
        exactly once across step()/run() calls."""
        try:
            done = self.engine.step()
        except (KeyboardInterrupt, SystemExit, AssertionError):
            raise               # the audit is never laundered
        except Exception as exc:  # noqa: BLE001 — supervised
            self._restart(exc)
            done = []
        out = []
        for r in done:
            if id(r) not in self._returned:
                self._returned.add(id(r))
                out.append(r)
        self.completed.extend(out)
        return out

    def run(self):
        """Drive to completion across restarts; returns every request
        completed by this call (tokens or typed error), exactly once.
        Requests that finished before a budget-exhausting failure stay
        reachable on ``self.completed`` even when the failure
        propagates — a finished stream never just disappears."""
        done: list = []

        def absorb(reqs):
            for r in reqs:
                if id(r) not in self._returned:
                    self._returned.add(id(r))
                    done.append(r)

        try:
            while True:
                try:
                    absorb(self.engine.run())
                except (KeyboardInterrupt, SystemExit,
                        AssertionError):
                    # AssertionError is the page-accounting audit
                    # speaking — the engine refuses to contain it and
                    # the supervisor must not launder it into a
                    # restart either
                    raise
                except Exception as exc:  # noqa: BLE001 — supervised
                    absorb(self.engine.completed)
                    self._restart(exc)
                    continue
                absorb(self.engine.completed)
                leftover = [r for r in self.engine.slot_req
                            if r is not None and not r.finished]
                if leftover:
                    # a clean return with occupants left behind is an
                    # engine fault too (a slot that never drained)
                    self._restart(RuntimeError(
                        f"engine run() returned with {len(leftover)} "
                        f"undrained slot(s)"))
                    continue
                return done
        finally:
            self.completed.extend(done)

    def _restart(self, exc):
        """Tear down + rebuild, or re-raise once the budget is spent."""
        rec = _frec.get_recorder()
        if rec is not None:
            try:
                rec.dump(f"engine supervisor restart: {exc!r}")
            except OSError:
                pass           # post-mortem is best-effort
        # budget check BEFORE the counter: the budget-exceeded
        # terminal attempt is not a restart cycle that happened
        if self.restarts >= self.max_restarts:
            raise exc
        self.restarts += 1
        reg = _metrics.get_registry()
        reg.counter("restart/engine_restarts").inc()
        old = self.engine
        try:
            g = old.gauges()
            for k in self._COUNTER_GAUGES:
                self._carried[k] = self._carried.get(k, 0) \
                    + int(g.get(k, 0))
        except Exception:  # noqa: BLE001 — a dead engine's gauges are
            pass           # best-effort salvage, never block restart
        # replay in arrival order so FIFO fairness survives the restart
        salvage = salvage_unfinished(old)
        for r in salvage:
            # the trace hop that distinguishes "my engine was rebuilt
            # under me" from a scheduler preemption (ISSUE 13)
            record_hop(r, "engine_restart", attempt=self.restarts,
                       replica=getattr(old, "_fleet_replica_id", None),
                       tokens=len(r.tokens), error=repr(exc)[:80])
        self.engine = self._factory()
        # carry the dead engine's id counter: requeue() only advances
        # past SALVAGED ids, and a fresh engine re-minting an id the
        # old engine already completed would conflate two requests in
        # any client map keyed by request_id
        self.engine._next_id = max(self.engine._next_id, old._next_id)
        for r in salvage:
            self.engine.requeue(r)
        reg.counter("restart/engine_requeued").inc(len(salvage))
        _frec.record_event("engine_restart", attempt=self.restarts,
                           requeued=len(salvage),
                           error=repr(exc)[:200])
