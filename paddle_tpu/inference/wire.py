"""Length-prefixed JSON frame protocol for process-backed replicas
(ISSUE 16).

One parent (:class:`~paddle_tpu.inference.proc_replica.ProcReplica`)
and one worker (``python -m paddle_tpu.inference.worker``) speak this
protocol over a local ``socketpair``. The wire is treated as HOSTILE:
frames carry a magic, an explicit length and a CRC32, every payload is
a JSON object with a strictly-increasing per-direction sequence
number, and every way a frame can be wrong — truncated, oversized,
garbage bytes, bit-flipped, duplicated, reordered — surfaces as a
TYPED :class:`WireError` subclass, never a hang and never a silently
half-applied message. After a corrupt stretch the decoder RESYNCS by
scanning forward to the next magic, so one mangled frame costs one
typed error, not the connection.

Frame layout (all integers big-endian)::

    MAGIC(2) | length(4) | crc32(4) | payload = JSON utf-8

Fault hooks: :func:`add_fault_hook` registers a process-local callable
``hook(replica_id, direction, data) -> data | None`` consulted by
PARENT-side transports on every send (``direction="tx"``) and every
socket read (``"rx"``); returning ``None`` drops the bytes, returning
different bytes corrupts them, and sleeping inside the hook delays
them. This is the injection point for the FaultInjector's
``drop_frame`` / ``delay_frame`` / ``corrupt_frame`` plans — the
production code path is exercised unmodified.

Stdlib only by design: the worker boundary must not grow a dependency
the parent cannot guarantee.
"""

from __future__ import annotations

import json
import select
import socket
import threading
import zlib

MAGIC = b"\xa5\x5a"
_HEADER = len(MAGIC) + 4 + 4
#: frames above this are a protocol violation (a corrupt length field
#: reads as a huge allocation request — reject, resync, move on)
MAX_FRAME = 8 * 1024 * 1024


class WireError(RuntimeError):
    """Base of every typed wire failure (never raised bare)."""


class FrameCorrupt(WireError):
    """Bad magic, bad CRC, or a payload that is not a JSON object."""


class FrameTooLarge(WireError):
    """Length field exceeds ``MAX_FRAME`` — framing is lost."""


class FrameOutOfOrder(WireError):
    """Sequence number not strictly increasing (duplicate or replay)."""


class WireTimeout(WireError):
    """No complete frame within the caller's deadline."""


class WireClosed(WireError):
    """Peer EOF or a dead socket — the worker is gone."""


# ---- fault hooks (FaultInjector seam) --------------------------------------

_fault_hooks: list = []
_hooks_lock = threading.Lock()


def add_fault_hook(hook):
    """Register ``hook(replica_id, direction, data) -> data | None``
    (see module docstring). Returns the hook for ``remove``."""
    with _hooks_lock:
        _fault_hooks.append(hook)
    return hook


def remove_fault_hook(hook):
    with _hooks_lock:
        try:
            _fault_hooks.remove(hook)
        except ValueError:
            pass


def _apply_hooks(replica_id, direction, data):
    with _hooks_lock:
        hooks = list(_fault_hooks)
    for hook in hooks:
        if data is None:
            break
        data = hook(replica_id, direction, data)
    return data


# ---- framing ---------------------------------------------------------------

def encode_frame(obj) -> bytes:
    payload = json.dumps(obj, separators=(",", ":")).encode("utf-8")
    if len(payload) > MAX_FRAME:
        raise FrameTooLarge(
            f"payload {len(payload)} bytes exceeds MAX_FRAME "
            f"{MAX_FRAME}")
    return (MAGIC + len(payload).to_bytes(4, "big")
            + zlib.crc32(payload).to_bytes(4, "big") + payload)


class FrameDecoder:
    """Incremental decoder with resync. ``feed`` bytes in any
    chunking; ``next_frame`` yields one payload (bytes) or ``None``
    when more input is needed, raising a typed :class:`WireError` for
    each corrupt stretch AFTER advancing past it — the caller can keep
    calling and the next intact frame still decodes."""

    def __init__(self, max_frame=MAX_FRAME):
        self._buf = bytearray()
        self._max = int(max_frame)
        self.errors = 0

    def feed(self, data: bytes):
        self._buf += data

    def pending(self) -> int:
        return len(self._buf)

    def _resync(self, skip):
        """Drop ``skip`` bytes, then everything up to the next magic;
        returns how many bytes were discarded in total."""
        del self._buf[:skip]
        idx = self._buf.find(MAGIC)
        if idx < 0:
            # keep the last byte: it may be the first half of a magic
            # split across reads
            keep = 1 if self._buf[-1:] == MAGIC[:1] else 0
            dropped = skip + len(self._buf) - keep
            del self._buf[:len(self._buf) - keep]
            return dropped
        del self._buf[:idx]
        return skip + idx

    def next_frame(self):
        if len(self._buf) < _HEADER:
            if self._buf and not MAGIC.startswith(
                    bytes(self._buf[:2])):
                self.errors += 1
                n = self._resync(1)
                raise FrameCorrupt(f"bad magic ({n} bytes dropped)")
            return None
        if bytes(self._buf[:2]) != MAGIC:
            self.errors += 1
            n = self._resync(1)
            raise FrameCorrupt(f"bad magic ({n} bytes dropped)")
        length = int.from_bytes(self._buf[2:6], "big")
        if length > self._max:
            self.errors += 1
            self._resync(2)
            raise FrameTooLarge(
                f"frame length {length} exceeds {self._max}")
        if len(self._buf) < _HEADER + length:
            return None
        crc = int.from_bytes(self._buf[6:10], "big")
        payload = bytes(self._buf[_HEADER:_HEADER + length])
        if zlib.crc32(payload) != crc:
            self.errors += 1
            # the length field itself is untrusted after a CRC
            # mismatch: drop only the magic and rescan
            self._resync(2)
            raise FrameCorrupt("crc mismatch")
        del self._buf[:_HEADER + length]
        return payload


# ---- transport -------------------------------------------------------------

class WireTransport:
    """One socket endpoint: thread-safe framed ``send`` (the worker's
    heartbeat thread and RPC loop share one transport) and deadline-
    bounded ``recv``. ``side="parent"`` consults the fault hooks;
    the worker side never does (hooks are a parent-process test
    seam)."""

    def __init__(self, sock, replica_id=None, side="parent",
                 max_frame=MAX_FRAME):
        self.sock = sock
        self.replica_id = replica_id
        self.side = side
        self._dec = FrameDecoder(max_frame)
        self._send_lock = threading.Lock()
        self._send_seq = 0
        self._recv_seq = -1
        self._closed = False
        sock.setblocking(False)

    # -- send ----------------------------------------------------------

    def send(self, obj: dict):
        """Frame and send one JSON object (a ``seq`` is stamped in).
        Raises :class:`WireClosed` on a dead socket."""
        with self._send_lock:
            if self._closed:
                raise WireClosed("transport closed")
            obj = dict(obj)
            obj["seq"] = self._send_seq
            self._send_seq += 1
            data = encode_frame(obj)
            if self.side == "parent":
                data = _apply_hooks(self.replica_id, "tx", data)
                if data is None:
                    return           # dropped on the (injected) floor
            try:
                self._sendall(data)
            except (BrokenPipeError, ConnectionError, OSError) as e:
                raise WireClosed(f"send failed: {e}") from e

    def _sendall(self, data):
        # non-blocking socket: spin sendall by hand with short waits
        view = memoryview(data)
        while view:
            try:
                n = self.sock.send(view)
                view = view[n:]
            except BlockingIOError:
                select.select([], [self.sock], [], 0.5)

    # -- recv ----------------------------------------------------------

    def recv(self, timeout_s: float) -> dict:
        """One decoded, sequence-checked JSON object within
        ``timeout_s`` seconds. Raises :class:`WireTimeout`,
        :class:`WireClosed`, or a frame-level :class:`WireError`
        (after which the decoder has already resynced — call again)."""
        import time
        deadline = time.monotonic() + max(0.0, float(timeout_s))
        while True:
            payload = self._dec.next_frame()   # may raise (resynced)
            if payload is not None:
                return self._validate(payload)
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                raise WireTimeout(
                    f"no frame within {timeout_s:.3f}s")
            try:
                r, _, _ = select.select([self.sock], [], [],
                                        min(remaining, 0.5))
            except (OSError, ValueError) as e:
                raise WireClosed(f"socket dead: {e}") from e
            if not r:
                continue
            try:
                data = self.sock.recv(65536)
            except BlockingIOError:
                continue
            except (ConnectionError, OSError) as e:
                raise WireClosed(f"recv failed: {e}") from e
            if not data:
                raise WireClosed("peer EOF")
            if self.side == "parent":
                data = _apply_hooks(self.replica_id, "rx", data)
                if data is None:
                    continue
            self._dec.feed(data)

    def _validate(self, payload):
        try:
            obj = json.loads(payload.decode("utf-8"))
        except (ValueError, UnicodeDecodeError) as e:
            raise FrameCorrupt(f"payload is not JSON: {e}") from e
        if not isinstance(obj, dict) or not isinstance(
                obj.get("seq"), int):
            raise FrameCorrupt("payload is not a sequenced object")
        seq = obj["seq"]
        if seq <= self._recv_seq:
            raise FrameOutOfOrder(
                f"seq {seq} after {self._recv_seq} (duplicate or "
                f"replayed frame)")
        self._recv_seq = seq
        return obj

    @property
    def wire_errors(self) -> int:
        return self._dec.errors

    def close(self):
        with self._send_lock:
            self._closed = True
        try:
            self.sock.close()
        except OSError:
            pass


def socketpair():
    """A connected AF_UNIX pair (parent end, worker end)."""
    a, b = socket.socketpair(socket.AF_UNIX, socket.SOCK_STREAM)
    return a, b
