"""Length-prefixed JSON frame protocol for process-backed replicas
(ISSUE 16).

One parent (:class:`~paddle_tpu.inference.proc_replica.ProcReplica`)
and one worker (``python -m paddle_tpu.inference.worker``) speak this
protocol over a local ``socketpair``. The wire is treated as HOSTILE:
frames carry a magic, an explicit length and a CRC32, every payload is
a JSON object with a strictly-increasing per-direction sequence
number, and every way a frame can be wrong — truncated, oversized,
garbage bytes, bit-flipped, duplicated, reordered — surfaces as a
TYPED :class:`WireError` subclass, never a hang and never a silently
half-applied message. After a corrupt stretch the decoder RESYNCS by
scanning forward to the next magic, so one mangled frame costs one
typed error, not the connection.

Frame layout (all integers big-endian)::

    MAGIC(2) | length(4) | crc32(4) | payload = JSON utf-8

Fault hooks: :func:`add_fault_hook` registers a process-local callable
``hook(replica_id, direction, data) -> data | None`` consulted by
PARENT-side transports on every send (``direction="tx"``) and every
socket read (``"rx"``); returning ``None`` drops the bytes, returning
different bytes corrupts them, and sleeping inside the hook delays
them. This is the injection point for the FaultInjector's
``drop_frame`` / ``delay_frame`` / ``corrupt_frame`` plans — the
production code path is exercised unmodified.

Stdlib only by design: the worker boundary must not grow a dependency
the parent cannot guarantee.
"""

from __future__ import annotations

import base64
import json
import select
import socket
import threading
import zlib

MAGIC = b"\xa5\x5a"
_HEADER = len(MAGIC) + 4 + 4
#: frames above this are a protocol violation (a corrupt length field
#: reads as a huge allocation request — reject, resync, move on).
#: Default only: both the decoder and the transport take ``max_frame``
#: as a constructor knob (ISSUE 17 — KV-page transfers size the cap to
#: the page geometry instead of living with one global constant).
MAX_FRAME = 8 * 1024 * 1024

#: partial chunked-payload groups kept per transport while awaiting
#: their remaining chunks; beyond this the OLDEST group is discarded
#: (its sender's retransmit arrives under a fresh transfer id, so a
#: group orphaned by a corrupt chunk can never pin memory forever)
MAX_PARTIAL_CHUNK_GROUPS = 4


class WireError(RuntimeError):
    """Base of every typed wire failure (never raised bare)."""


class FrameCorrupt(WireError):
    """Bad magic, bad CRC, or a payload that is not a JSON object."""


class FrameTooLarge(WireError):
    """Length field exceeds ``MAX_FRAME`` — framing is lost."""


class FrameOutOfOrder(WireError):
    """Sequence number not strictly increasing (duplicate or replay)."""


class WireTimeout(WireError):
    """No complete frame within the caller's deadline."""


class WireClosed(WireError):
    """Peer EOF or a dead socket — the worker is gone."""


# ---- fault hooks (FaultInjector seam) --------------------------------------

_fault_hooks: list = []
_hooks_lock = threading.Lock()


def add_fault_hook(hook):
    """Register ``hook(replica_id, direction, data) -> data | None``
    (see module docstring). Returns the hook for ``remove``."""
    with _hooks_lock:
        _fault_hooks.append(hook)
    return hook


def remove_fault_hook(hook):
    with _hooks_lock:
        try:
            _fault_hooks.remove(hook)
        except ValueError:
            pass


def _apply_hooks(replica_id, direction, data):
    with _hooks_lock:
        hooks = list(_fault_hooks)
    for hook in hooks:
        if data is None:
            break
        data = hook(replica_id, direction, data)
    return data


# ---- framing ---------------------------------------------------------------

def encode_frame(obj, max_frame=MAX_FRAME) -> bytes:
    payload = json.dumps(obj, separators=(",", ":")).encode("utf-8")
    if len(payload) > max_frame:
        raise FrameTooLarge(
            f"payload {len(payload)} bytes exceeds frame cap "
            f"{max_frame}")
    return (MAGIC + len(payload).to_bytes(4, "big")
            + zlib.crc32(payload).to_bytes(4, "big") + payload)


class FrameDecoder:
    """Incremental decoder with resync. ``feed`` bytes in any
    chunking; ``next_frame`` yields one payload (bytes) or ``None``
    when more input is needed, raising a typed :class:`WireError` for
    each corrupt stretch AFTER advancing past it — the caller can keep
    calling and the next intact frame still decodes."""

    def __init__(self, max_frame=MAX_FRAME):
        self._buf = bytearray()
        self._max = int(max_frame)
        self.errors = 0

    def feed(self, data: bytes):
        self._buf += data

    def pending(self) -> int:
        return len(self._buf)

    def _resync(self, skip):
        """Drop ``skip`` bytes, then everything up to the next magic;
        returns how many bytes were discarded in total."""
        del self._buf[:skip]
        idx = self._buf.find(MAGIC)
        if idx < 0:
            # keep the last byte: it may be the first half of a magic
            # split across reads
            keep = 1 if self._buf[-1:] == MAGIC[:1] else 0
            dropped = skip + len(self._buf) - keep
            del self._buf[:len(self._buf) - keep]
            return dropped
        del self._buf[:idx]
        return skip + idx

    def next_frame(self):
        if len(self._buf) < _HEADER:
            if self._buf and not MAGIC.startswith(
                    bytes(self._buf[:2])):
                self.errors += 1
                n = self._resync(1)
                raise FrameCorrupt(f"bad magic ({n} bytes dropped)")
            return None
        if bytes(self._buf[:2]) != MAGIC:
            self.errors += 1
            n = self._resync(1)
            raise FrameCorrupt(f"bad magic ({n} bytes dropped)")
        length = int.from_bytes(self._buf[2:6], "big")
        if length > self._max:
            self.errors += 1
            self._resync(2)
            raise FrameTooLarge(
                f"frame length {length} exceeds {self._max}")
        if len(self._buf) < _HEADER + length:
            return None
        crc = int.from_bytes(self._buf[6:10], "big")
        payload = bytes(self._buf[_HEADER:_HEADER + length])
        if zlib.crc32(payload) != crc:
            self.errors += 1
            # the length field itself is untrusted after a CRC
            # mismatch: drop only the magic and rescan
            self._resync(2)
            raise FrameCorrupt("crc mismatch")
        del self._buf[:_HEADER + length]
        return payload


# ---- transport -------------------------------------------------------------

class WireTransport:
    """One socket endpoint: thread-safe framed ``send`` (the worker's
    heartbeat thread and RPC loop share one transport) and deadline-
    bounded ``recv``. ``side="parent"`` consults the fault hooks;
    the worker side never does (hooks are a parent-process test
    seam).

    Chunked payloads (ISSUE 17): a payload whose JSON encoding would
    overflow ``max_frame`` is transparently split into a multi-frame
    group — each chunk is an ordinary sequenced, CRC'd frame carrying
    a base64 slice plus ``{"_chunk": {"xid", "i", "n"}}`` — and
    :meth:`recv` reassembles the group before returning the decoded
    object. A corrupt chunk surfaces exactly like any corrupt frame
    (typed error, decoder resynced); the orphaned partial group is
    bounded by ``MAX_PARTIAL_CHUNK_GROUPS`` and the sender's
    retransmit arrives under a fresh transfer id, so chunking never
    adds a hang or a half-applied message to the fault model."""

    def __init__(self, sock, replica_id=None, side="parent",
                 max_frame=MAX_FRAME, chunk_bytes=None):
        self.sock = sock
        self.replica_id = replica_id
        self.side = side
        self.max_frame = int(max_frame)
        # raw-byte slice per chunk; sized so the b64 expansion (4/3)
        # plus the JSON envelope stays comfortably under the cap
        self.chunk_bytes = int(chunk_bytes) if chunk_bytes \
            else max(1, (self.max_frame // 2))
        self._dec = FrameDecoder(max_frame)
        self._send_lock = threading.Lock()
        self._send_seq = 0
        self._recv_seq = -1
        self._next_xid = 0
        self._partial = {}   # xid -> {"n": int, "parts": {i: bytes}}
        self._closed = False
        sock.setblocking(False)

    # -- send ----------------------------------------------------------

    def send(self, obj: dict):
        """Frame and send one JSON object (a ``seq`` is stamped in),
        transparently splitting into a chunked multi-frame group when
        the encoding would overflow the frame cap. Raises
        :class:`WireClosed` on a dead socket."""
        with self._send_lock:
            if self._closed:
                raise WireClosed("transport closed")
            payload = json.dumps(
                obj, separators=(",", ":")).encode("utf-8")
            # headroom for the seq stamp the single-frame path adds
            if len(payload) + 64 > self.max_frame:
                self._send_chunked(payload)
                return
            obj = dict(obj)
            obj["seq"] = self._send_seq
            self._send_seq += 1
            self._send_raw(encode_frame(obj, self.max_frame))

    def _send_chunked(self, payload: bytes):
        """Split ``payload`` (the un-stamped JSON bytes) into a
        multi-frame chunk group. Caller holds the send lock."""
        xid = self._next_xid
        self._next_xid += 1
        pieces = [payload[i:i + self.chunk_bytes]
                  for i in range(0, len(payload), self.chunk_bytes)]
        for i, piece in enumerate(pieces):
            frame = {"_chunk": {"xid": xid, "i": i,
                                "n": len(pieces)},
                     "d": base64.b64encode(piece).decode("ascii"),
                     "seq": self._send_seq}
            self._send_seq += 1
            self._send_raw(encode_frame(frame, self.max_frame))

    def _send_raw(self, data: bytes):
        if self.side == "parent":
            data = _apply_hooks(self.replica_id, "tx", data)
            if data is None:
                return               # dropped on the (injected) floor
        try:
            self._sendall(data)
        except (BrokenPipeError, ConnectionError, OSError) as e:
            raise WireClosed(f"send failed: {e}") from e

    def _sendall(self, data):
        # non-blocking socket: spin sendall by hand with short waits
        view = memoryview(data)
        while view:
            try:
                n = self.sock.send(view)
                view = view[n:]
            except BlockingIOError:
                select.select([], [self.sock], [], 0.5)

    # -- recv ----------------------------------------------------------

    def recv(self, timeout_s: float) -> dict:
        """One decoded, sequence-checked JSON object within
        ``timeout_s`` seconds. Raises :class:`WireTimeout`,
        :class:`WireClosed`, or a frame-level :class:`WireError`
        (after which the decoder has already resynced — call again)."""
        import time
        deadline = time.monotonic() + max(0.0, float(timeout_s))
        while True:
            payload = self._dec.next_frame()   # may raise (resynced)
            if payload is not None:
                obj = self._validate(payload)
                if "_chunk" in obj:
                    whole = self._absorb_chunk(obj)
                    if whole is None:
                        continue     # group incomplete — keep reading
                    return whole
                return obj
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                raise WireTimeout(
                    f"no frame within {timeout_s:.3f}s")
            try:
                r, _, _ = select.select([self.sock], [], [],
                                        min(remaining, 0.5))
            except (OSError, ValueError) as e:
                raise WireClosed(f"socket dead: {e}") from e
            if not r:
                continue
            try:
                data = self.sock.recv(65536)
            except BlockingIOError:
                continue
            except (ConnectionError, OSError) as e:
                raise WireClosed(f"recv failed: {e}") from e
            if not data:
                raise WireClosed("peer EOF")
            if self.side == "parent":
                data = _apply_hooks(self.replica_id, "rx", data)
                if data is None:
                    continue
            self._dec.feed(data)

    def _validate(self, payload):
        try:
            obj = json.loads(payload.decode("utf-8"))
        except (ValueError, UnicodeDecodeError) as e:
            raise FrameCorrupt(f"payload is not JSON: {e}") from e
        if not isinstance(obj, dict) or not isinstance(
                obj.get("seq"), int):
            raise FrameCorrupt("payload is not a sequenced object")
        seq = obj["seq"]
        if seq <= self._recv_seq:
            raise FrameOutOfOrder(
                f"seq {seq} after {self._recv_seq} (duplicate or "
                f"replayed frame)")
        self._recv_seq = seq
        return obj

    def _absorb_chunk(self, obj):
        """Fold one chunk frame into its partial group; returns the
        reassembled, decoded payload when the group completes, else
        ``None``. A malformed chunk envelope is a corrupt frame."""
        meta = obj.get("_chunk")
        try:
            xid, i, n = (int(meta["xid"]), int(meta["i"]),
                         int(meta["n"]))
            piece = base64.b64decode(obj["d"], validate=True)
        except (TypeError, KeyError, ValueError) as e:
            raise FrameCorrupt(f"bad chunk envelope: {e}") from e
        if n <= 0 or not (0 <= i < n):
            raise FrameCorrupt(f"bad chunk index {i}/{n}")
        group = self._partial.get(xid)
        if group is None:
            group = self._partial[xid] = {"n": n, "parts": {}}
            while len(self._partial) > MAX_PARTIAL_CHUNK_GROUPS:
                # oldest first — insertion order IS arrival order
                self._partial.pop(next(iter(self._partial)))
        if group["n"] != n:
            # two sizes claimed for one transfer id: framing is lying
            self._partial.pop(xid, None)
            raise FrameCorrupt(
                f"chunk group {xid} changed size {group['n']}->{n}")
        group["parts"][i] = piece
        if len(group["parts"]) < n:
            return None
        self._partial.pop(xid, None)
        whole = b"".join(group["parts"][k] for k in range(n))
        try:
            inner = json.loads(whole.decode("utf-8"))
        except (ValueError, UnicodeDecodeError) as e:
            raise FrameCorrupt(
                f"reassembled payload is not JSON: {e}") from e
        if not isinstance(inner, dict):
            raise FrameCorrupt("reassembled payload is not an object")
        # the group's last frame seq stands in for the whole payload
        # (chunk frames were individually sequence-checked already)
        inner.setdefault("seq", self._recv_seq)
        return inner

    @property
    def wire_errors(self) -> int:
        return self._dec.errors

    def close(self):
        with self._send_lock:
            self._closed = True
        try:
            self.sock.close()
        except OSError:
            pass


def socketpair():
    """A connected AF_UNIX pair (parent end, worker end)."""
    a, b = socket.socketpair(socket.AF_UNIX, socket.SOCK_STREAM)
    return a, b
