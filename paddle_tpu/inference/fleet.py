"""Fault-tolerant multi-replica serving fleet (ISSUE 11).

One :class:`~paddle_tpu.inference.serving.ContinuousBatchingEngine` is
one chip's worth of traffic; the ROADMAP's "millions of users" run N
engines behind a router. This module is that tier, with the robustness
story foregrounded: a dead, wedged, or merely slow replica must degrade
the fleet gracefully — mirroring, on the serving side, the elastic
training guarantees of PR 6.

Structure, outside-in:

- :class:`ServingFleet` — owns N :class:`FleetReplica` handles and a
  fault-tolerant ROUTER. Dispatch is least-loaded/latency-aware,
  driven by each replica's PR-9 metrics registry (outstanding
  generation work per slot as the load signal, the ``serving/ttft_ms``
  reservoir p99 as the latency tiebreak). A **prefix-affinity hint**
  (ISSUE 12) breaks load ties toward the replica that last served the
  same first-page prefix-hash — its prefix cache is warm — strictly
  below health and least-loaded, never overriding circuit-breaker
  state. Admission per replica rides
  the PR-10 :class:`~.reliability.AdmissionController`; when EVERY
  ready replica sheds, the fleet raises
  :class:`~.reliability.Overloaded` with ``retry_after_s`` = the MAX
  of the controllers' computed retry-afters (not a constant — the
  ISSUE-11 propagation fix), and the fleet's own retry backoff honors
  that value as a floor.
- **Health model** — two distinct checks, deliberately separate:

  * *liveness* rides the flight-recorder watchdog: ``run()`` arms it
    and beats once per fleet turn, so a replica step that HANGS (a
    stuck device fetch) stops the beats and dumps a diagnosable
    bundle — the heartbeat path;
  * *progress* is the fleet's own no-progress check: a replica whose
    steps keep returning (heartbeats fine) but whose observable state
    (tokens, completions, admissions, queue, occupancy, restarts) has
    not moved for ``no_progress_turns`` consecutive turns WHILE it has
    work is **wedged** — it is ejected and its queue drains to
    siblings, without ever tripping the engine's true-deadlock stall
    diagnostic (``engine.step()`` has no stall path; only ``run()``
    does).

- **Failover** — a replica death inside the step is absorbed by its
  PR-10 :class:`~.reliability.EngineSupervisor` (salvage + rebuild +
  idempotent replay from prompt + emitted tokens). Past the
  supervisor's ``max_restarts`` budget the failure escapes and the
  fleet opens the replica's **circuit breaker**: the replica is
  ejected, its queue + in-flight requests are salvaged
  (:func:`~.reliability.salvage_unfinished`) and re-routed to siblings
  under **bounded retries with exponential backoff + jitter**. Replays
  carry their already-emitted tokens through the engines' recompute
  path, so a greedy stream is token-identical across a failover
  (pinned by ``tests/test_fleet_reliability.py``). A request whose
  retry budget is spent completes with the typed
  :class:`~.reliability.ReplicaFailed` — it never just vanishes.
- **Hedged dispatch** — a request still waiting for its first token
  after a p99-derived delay (``hedge_factor`` x the BEST ready
  replica's ttft p99 — the best, so a straggler cannot inflate its own
  hedge threshold) is duplicated to a sibling; the first completion
  wins and cancels the loser via the PR-10 ``cancel()`` path. Exactly
  one completion is ever delivered per fleet id.
- **Elasticity** — :meth:`ServingFleet.scale_down` stops admission to
  a replica (router weight drops immediately), lets in-flight requests
  finish under a deadline, then evicts stragglers through the engine's
  ``handoff()`` hook for recompute on siblings; :meth:`scale_up`
  registers a cold replica and WARMS it (compiles its programs on a
  sacrificial request, then resets its gauges so warmup latencies
  cannot pollute the routing signal) before it takes weight.

:class:`FleetReplica` is the **process-worker seam**: the fleet talks
to a replica only through ``admit/step/salvage/load/health`` surfaces,
so a future process-backed replica (engine in a worker process behind
an RPC transport, or the prefill/decode-disaggregated worker of
ROADMAP item 2) implements the same contract without touching the
router. The in-process handle is also what makes the chaos tests
deterministic.
"""

from __future__ import annotations

import random
import time
from dataclasses import dataclass, field

import numpy as np

from ..profiler import flight_recorder as _frec
from ..profiler import metrics as _pmetrics
from ..profiler.slo import SLOTracker
from ..profiler.trace import get_trace_log, get_tracer
from .reliability import (AdmissionController, DeadlineExceeded,
                          EngineSupervisor, Overloaded, ReplicaFailed,
                          RequestCancelled, salvage_unfinished)
from .serving import ServedRequest, record_hop, request_trace_summary

__all__ = ["ServingFleet", "FleetReplica"]

# the fleet metric vocabulary (docs/observability.md table;
# tools/check_metric_names.py lints these literals). Each fleet owns a
# PRIVATE MetricsRegistry of these.
_pmetrics.declare("fleet/submitted", "counter",
                  "requests accepted by the fleet router (fleet-global "
                  "ids)")
_pmetrics.declare("fleet/completed", "counter",
                  "fleet requests delivered exactly once (tokens or "
                  "typed error)")
_pmetrics.declare("fleet/shed_rejections", "counter",
                  "fleet submissions rejected Overloaded: every ready "
                  "replica shed (retry-after = max across replicas) or "
                  "no replica takes weight (all breakers open)")
_pmetrics.declare("fleet/retries", "counter",
                  "failover replays scheduled after a request's "
                  "replica died or wedged (bounded exponential backoff "
                  "with jitter)")
_pmetrics.declare("fleet/requeued", "counter",
                  "queued + in-flight requests salvaged off a dead, "
                  "wedged or drained replica for re-routing to "
                  "siblings")
_pmetrics.declare("fleet/hedges", "counter",
                  "hedged duplicate dispatches launched against "
                  "straggler replicas (p99-derived delay)")
_pmetrics.declare("fleet/hedge_wins", "counter",
                  "completions delivered by the hedge copy (the "
                  "duplicate beat the straggler)")
_pmetrics.declare("fleet/hedge_cancels", "counter",
                  "losing hedge copies cancelled after the winner "
                  "finished (PR-10 cancel path)")
_pmetrics.declare("fleet/breaker_open", "counter",
                  "circuit breakers tripped: replica ejected after its "
                  "supervisor restart budget was spent")
_pmetrics.declare("fleet/wedge_ejections", "counter",
                  "replicas ejected by the no-progress health check "
                  "(heartbeats arriving, nothing moving)")
_pmetrics.declare("fleet/drains", "counter",
                  "graceful scale-down drains completed (clean, or "
                  "deadline-evicted onto siblings)")
_pmetrics.declare("fleet/scale_ups", "counter",
                  "replicas registered and warmed by scale_up before "
                  "taking router weight")
_pmetrics.declare("fleet/affinity_hits", "counter",
                  "requests routed to the replica that last served "
                  "their prefix-hash (ISSUE-12 prefix-affinity hint: "
                  "a load/health tie-break, so the hit lands on a "
                  "warm prefix cache)")
_pmetrics.declare("fleet/replicas_ready", "gauge",
                  "replicas currently taking router weight")
_pmetrics.declare("fleet/queue_depth", "gauge",
                  "requests waiting in admission queues summed across "
                  "live replicas — the fleet-level pressure signal the "
                  "autoscaler and /statusz read (ISSUE 19)")
_pmetrics.declare("fleet/shed_rate", "gauge",
                  "admission sheds per second summed across live "
                  "replicas (each controller's trailing-window rate)")
_pmetrics.declare("fleet/failover_ms", "histogram",
                  "per salvaged request: replica ejection -> "
                  "re-admission on a sibling, ms — retry backoff "
                  "included (bounded reservoir)")


class FleetReplica:
    """One in-process serving replica: an EngineSupervisor-wrapped
    engine plus its admission controller and health/progress state.

    The engine is tagged with ``_fleet_replica_id`` (re-applied on
    every supervised rebuild) so replica-level fault plans
    (``FaultInjector.kill_replica`` / ``wedge_replica`` /
    ``slow_replica``) can target exactly one replica of a shared
    engine class.

    States: ``ready`` (takes router weight) → ``draining`` (admission
    stopped, in-flight finishing) → ``retired`` (clean scale-down) |
    ``ejected`` (breaker open / wedged); ``warming`` while
    :meth:`ServingFleet.scale_up` compiles its programs.
    """

    def __init__(self, replica_id, engine_factory, *, max_restarts=2,
                 max_queue=64, default_ttft_slo_s=None,
                 min_retry_after_s=0.05):
        self.id = int(replica_id)

        def build():
            eng = engine_factory()
            eng._fleet_replica_id = self.id
            return eng

        self.supervisor = EngineSupervisor(build,
                                           max_restarts=max_restarts)
        self.admission = AdmissionController(
            self.supervisor, max_queue=max_queue,
            default_ttft_slo_s=default_ttft_slo_s,
            min_retry_after_s=min_retry_after_s)
        self.state = "ready"
        self.drain_deadline = None
        #: why this replica left the fleet ("breaker" / "wedge" /
        #: "operator"); None while live — the /statusz health render
        self.eject_kind = None
        self.last_beat = time.perf_counter()
        self.last_progress = self.last_beat
        self._idle_marker = None
        self._stale_turns = 0

    @property
    def engine(self):
        return self.supervisor.engine

    def takes_weight(self):
        return self.state == "ready"

    def live(self):
        return self.state in ("ready", "draining")

    def has_work(self):
        eng = self.engine
        return bool(eng.queue) or any(
            r is not None and not r.finished for r in eng.slot_req)

    def load(self):
        """Router load signal: outstanding generation work (remaining
        tokens across queued + running requests), per slot — the
        least-loaded key."""
        eng = self.engine
        rem = sum(max(0, r.max_new_tokens - len(r.tokens))
                  for r in eng.queue)
        rem += sum(max(0, r.max_new_tokens - len(r.tokens))
                   for r in eng.slot_req
                   if r is not None and not r.finished)
        return rem / max(1, eng.num_slots)

    def queue_depth(self):
        """Requests waiting in this replica's admission queue — the
        per-replica pressure signal (ISSUE 19); the fleet mirrors it
        into the ``serving/queue_depth`` gauge each turn."""
        return len(self.engine.queue)

    def shed_rate(self):
        """This replica's windowed admission-shed rate (sheds/s) —
        :meth:`~.reliability.AdmissionController.shed_rate`."""
        return self.admission.shed_rate()

    def ttft_p99_s(self):
        """The replica's observed ttft p99 (PR-9 reservoir), seconds —
        the router's latency tiebreak and the hedge-delay input; None
        while cold."""
        h = self.engine.metrics.get("serving/ttft_ms")
        if h is None or h.count == 0:
            return None
        return h.percentile(99) / 1e3

    def _marker(self):
        """Progress fingerprint: any observable movement resets the
        no-progress clock (a supervised restart counts as movement —
        recovery in progress is not a wedge)."""
        eng = self.engine
        s = eng._stats
        return (s["tokens_emitted"], s["requests_completed"],
                s["prefills"], len(eng.queue),
                sum(r is not None for r in eng.slot_req),
                self.supervisor.restarts)

    def step(self):
        """One supervised scheduler turn. Returning at all stamps the
        liveness heartbeat; the progress clock advances only when the
        fingerprint moved. Raises past the supervisor's restart budget
        (the fleet opens the breaker)."""
        done = self.supervisor.step()
        self.last_beat = time.perf_counter()
        marker = self._marker()
        if done or marker != self._idle_marker:
            self._idle_marker = marker
            self._stale_turns = 0
            self.last_progress = self.last_beat
        elif self.has_work():
            self._stale_turns += 1
        return done

    def wedged(self, no_progress_turns):
        """The no-progress health check: work pending, heartbeats
        arriving, nothing moving for N consecutive turns."""
        return self.has_work() and self._stale_turns >= int(
            no_progress_turns)

    # -- disaggregation seam (ISSUE 17): the migration verbs a
    # role-aware fleet drives. In-process they reach the engine
    # directly; ProcReplica overrides them with kv_transfer RPCs over
    # the wire — the DisaggServingFleet router never knows which.

    def take_migrations(self):
        """Drain the replica's outbound (request, kv payload) pairs
        (empty for engines without the migration surface)."""
        eng = self.engine
        if hasattr(eng, "take_migrations"):
            return eng.take_migrations()
        return []

    def import_migration(self, req, payload):
        """Adopt a migrated request + its KV pages on this replica."""
        return self.engine.import_migration(req, payload)

    def release_exported(self, request_id):
        """Ack a completed transfer back to this (source) replica so
        its pinned exported pages become ordinary evictable cache."""
        eng = self.engine
        if hasattr(eng, "release_exported"):
            return eng.release_exported(request_id)
        return False

    def on_eject(self, kind):
        """Ejection hook for replica subclasses holding external
        resources (a process-backed replica reaps its worker here);
        no-op for the in-process replica."""

    def close(self):
        """Teardown hook (scale-down retire / fleet close); no-op for
        the in-process replica."""


@dataclass(eq=False)
class _Tracked:
    """Fleet-side view of one client request across attempts: the
    primary dispatch, an optional hedge copy, and failover replays."""

    fid: int
    prompt: np.ndarray
    max_new_tokens: int
    eos_token_id: int | None
    priority: int
    ttft_deadline_s: float | None
    deadline_s: float | None
    t_submit: float
    #: replica_id -> live ServedRequest attempt on that replica
    attempts: dict = field(default_factory=dict)
    #: salvaged attempt awaiting reassignment (tokens kept — the
    #: idempotent-replay payload)
    carry: ServedRequest | None = None
    retries: int = 0
    not_before: float = 0.0
    #: when the current carry was salvaged off its replica — the
    #: failover clock (fleet/failover_ms observes at re-admission)
    t_failed: float = 0.0
    hedged: bool = False
    hedge_rid: int | None = None
    #: first-page token-block hash (engine page_size granularity) —
    #: the ISSUE-12 prefix-affinity routing hint; None for prompts
    #: shorter than one page
    prefix_hash: int | None = None
    cancelled: bool = False
    last_error: Exception | None = None
    done: ServedRequest | None = None
    t_assign: float = 0.0
    #: SLO accounting label (ISSUE 13), copied onto every attempt
    tenant: str | None = None
    #: the ONE cross-replica hop list every attempt shares (the fleet
    #: trace: hedge winner + cancelled loser interleave here)
    hops: list = field(default_factory=list)


class ServingFleet:
    """N supervised engine replicas behind a fault-tolerant router
    (module docstring). ``engine_factory`` builds one replica's engine
    (same model/geometry for every replica); the fleet is driven
    cooperatively — :meth:`run` round-robins one supervised scheduler
    turn per live replica per fleet turn, which keeps every chaos
    scenario deterministic and is the contract a process-backed
    :class:`FleetReplica` would relax."""

    def __init__(self, engine_factory, num_replicas=2, *,
                 max_restarts=2, max_queue=64, default_ttft_slo_s=None,
                 min_retry_after_s=0.05, max_retries=3,
                 retry_backoff_s=0.02, retry_backoff_cap_s=2.0,
                 retry_jitter=0.25, hedge_delay_s=None,
                 hedge_factor=3.0, hedge_min_delay_s=0.05,
                 no_progress_turns=25, drain_deadline_s=30.0,
                 all_open_retry_after_s=1.0, seed=0, slo_rules=None,
                 replica_cls=None, replica_kwargs=None):
        self._factory = engine_factory
        #: the ISSUE-16 seam: a FleetReplica subclass (e.g.
        #: ProcReplica, whose "factory" is a worker spec dict) slots
        #: in here — the router below never knows the difference
        self._replica_cls = replica_cls or FleetReplica
        self._rep_kw = dict(max_restarts=int(max_restarts),
                            max_queue=int(max_queue),
                            default_ttft_slo_s=default_ttft_slo_s,
                            min_retry_after_s=float(min_retry_after_s))
        if replica_kwargs:
            self._rep_kw.update(replica_kwargs)
        self.max_retries = int(max_retries)
        self.retry_backoff_s = float(retry_backoff_s)
        self.retry_backoff_cap_s = float(retry_backoff_cap_s)
        self.retry_jitter = float(retry_jitter)
        self.hedge_delay_s = hedge_delay_s
        self.hedge_factor = float(hedge_factor)
        self.hedge_min_delay_s = float(hedge_min_delay_s)
        self.no_progress_turns = int(no_progress_turns)
        self.drain_deadline_s = float(drain_deadline_s)
        self.all_open_retry_after_s = float(all_open_retry_after_s)
        self._rng = random.Random(seed)
        #: the fleet's FEDERATION POINT (ISSUE 13): local fleet/*
        #: metrics live here, and every replica's private engine
        #: registry is a labeled source — /metrics and flight-recorder
        #: bundles read the whole fleet through this one handle
        self.metrics = _pmetrics.FederatedRegistry()
        #: per-tenant SLO accounting (profiler/slo.py); None without
        #: rules — attainment/burn gauges land in the federated
        #: registry so the exposition endpoint carries them
        self.slo = SLOTracker(slo_rules, registry=self.metrics) \
            if slo_rules else None
        #: self-measured observability overhead on the FLEET hot loop
        #: (SLO booking, trace-log feeds, tracer reconstruction) — the
        #: <2% obs/overhead_frac pin extends to the fleet tier
        self._obs_s = 0.0
        self._run_s = 0.0
        self.replicas: dict[int, FleetReplica] = {}
        self._next_replica_id = 0
        for _ in range(int(num_replicas)):
            self._add_replica(engine_factory)
        #: PENDING requests only — delivered entries are popped at
        #: _deliver, so the per-turn retry/hedge/reap scans and
        #: has_work() never degrade with the fleet's served history
        #: (the PR-9 memory-flat discipline; ``completed`` below is
        #: the caller-owned history, exactly like engine.completed)
        self._reqs: dict[int, _Tracked] = {}
        self._next_id = 0
        #: prefix-hash -> replica that last served it (ISSUE 12):
        #: the router's cache-affinity memory — bounded (LRU by
        #: insertion order) so a high-cardinality prefix stream cannot
        #: grow it without limit
        self._affinity: dict[int, int] = {}
        self._affinity_cap = 4096
        self.completed: list[ServedRequest] = []
        self._h_failover = self.metrics.histogram("fleet/failover_ms")

    # ---- replica registry ------------------------------------------------

    def _add_replica(self, factory, federate=True):
        rid = self._next_replica_id
        self._next_replica_id += 1
        rep = self._replica_cls(rid, factory, **self._rep_kw)
        self.replicas[rid] = rep
        if federate:
            self._federate(rep)
        return rep

    def _federate(self, rep):
        # federate the replica's private engine registry, read LIVE
        # through the supervisor (a rebuilt engine swaps the instance;
        # the federation watermark keeps the fleet totals monotonic)
        self.metrics.add_source(str(rep.id),
                                lambda rep=rep: rep.engine.metrics)

    # ---- the router door -------------------------------------------------

    def submit(self, prompt_ids, max_new_tokens, eos_token_id=None,
               priority=0, ttft_deadline_s=None,
               deadline_s=None, tenant=None) -> int:
        """Route one request to the best ready replica; returns the
        fleet-global request id. Raises :class:`ValueError` for a
        request no replica geometry can ever satisfy, and
        :class:`Overloaded` — ``retry_after_s`` = max of the
        controllers' computed retry-afters across the replicas that
        shed, or ``all_open_retry_after_s`` when no replica takes
        weight at all (all breakers open / everything draining)."""
        prompt = np.asarray(prompt_ids, np.int32).reshape(-1)
        ref = next((r for r in self.replicas.values() if r.live()),
                   None)
        if ref is not None:
            # structural validation once, against the shared geometry
            ref.engine._check_fits(prompt.size, int(max_new_tokens))
        fid = self._next_id
        tr = _Tracked(fid=fid, prompt=prompt,
                      max_new_tokens=int(max_new_tokens),
                      eos_token_id=eos_token_id,
                      priority=int(priority),
                      ttft_deadline_s=ttft_deadline_s,
                      deadline_s=deadline_s,
                      t_submit=time.perf_counter(),
                      tenant=tenant)
        # the trace is born HERE: one id, one hop list, shared by
        # every attempt this request will ever make (ISSUE 13)
        tr.hops.append({"kind": "submit", "t": tr.t_submit,
                        "tenant": tenant})
        # prefix-affinity hint (ISSUE 12): hash the first full page's
        # token block — requests sharing >= page_size prefix tokens
        # carry the same hash, and the engines' prefix caches index at
        # exactly this granularity
        if ref is not None:
            ps = int(getattr(ref.engine, "page_size", 0))
            if ps and prompt.size >= ps:
                tr.prefix_hash = hash(prompt[:ps].tobytes())
        self._assign(tr, self._make_attempt(tr))  # raises Overloaded
        self._next_id += 1   # only an accepted submission consumes an
        self._reqs[fid] = tr                # id (and is ever tracked)
        self.metrics.counter("fleet/submitted").inc()
        return fid

    def _make_attempt(self, tr):
        req = ServedRequest(tr.fid, tr.prompt, tr.max_new_tokens,
                            tr.eos_token_id, priority=tr.priority,
                            ttft_deadline_s=tr.ttft_deadline_s,
                            deadline_s=tr.deadline_s,
                            tenant=tr.tenant)
        req.t_arrive = tr.t_submit  # deadlines stay client-relative
        # fleet trace context: every attempt (the primary, a hedge
        # duplicate, a failover replay) carries the SAME trace id and
        # appends into the SAME hop list — the engines' admit/preempt/
        # finish hops from different replicas interleave into one
        # cross-replica timeline
        req.trace_id = tr.fid
        req.hops = tr.hops
        return req

    def _candidates(self, exclude=(), prefer=None):
        reps = [r for r in self.replicas.values()
                if r.takes_weight() and r.id not in exclude]
        # least outstanding work first; among equally-loaded healthy
        # replicas the prefix-affinity hint wins (the preferred
        # replica's prefix cache is warm for this prompt), then the
        # observed ttft p99, then id for determinism. Affinity sits
        # strictly BELOW health (non-ready replicas — breakers open,
        # draining — were never candidates) and below least-loaded:
        # a warm cache never outranks an idle sibling.
        reps.sort(key=lambda r: (r.load(),
                                 0 if r.id == prefer else 1,
                                 r.ttft_p99_s() or 0.0, r.id))
        return reps

    def _assign(self, tr, req, exclude=()):
        """Admit one attempt on the best replica that will take it;
        raises :class:`Overloaded` with the fleet-wide retry-after."""
        h = tr.prefix_hash
        prefer = self._affinity.get(h) if h is not None else None
        cands = self._candidates(exclude, prefer=prefer)
        if not cands:
            self.metrics.counter("fleet/shed_rejections").inc()
            raise Overloaded(
                "no replica taking weight (all breakers open or "
                "draining)", self.all_open_retry_after_s)
        retry_afters = []
        for rep in cands:
            try:
                rep.admission.admit(req)
            except Overloaded as exc:
                retry_afters.append(exc.retry_after_s)
                continue
            tr.attempts[rep.id] = req
            tr.t_assign = time.perf_counter()
            record_hop(req, "assign", replica=rep.id,
                       retries=tr.retries)
            if h is not None:
                if rep.id == prefer:
                    self.metrics.counter("fleet/affinity_hits").inc()
                # pop-then-insert moves a re-served prefix to the
                # dict's end, so the cap evicts the LEAST recently
                # used hash, not the hottest long-lived one
                self._affinity.pop(h, None)
                self._affinity[h] = rep.id
                if len(self._affinity) > self._affinity_cap:
                    self._affinity.pop(next(iter(self._affinity)))
            return rep.id
        self.metrics.counter("fleet/shed_rejections").inc()
        raise Overloaded(
            f"every ready replica shed ({len(cands)} tried)",
            max(retry_afters))

    # ---- lookup / cancel -------------------------------------------------

    def request(self, fid):
        """The live ServedRequest view of a fleet id: the carried
        replay or primary attempt while pending, the delivered
        completion afterwards (scanned from ``completed``, like
        ``engine.request``)."""
        tr = self._reqs.get(fid)
        if tr is not None:
            if tr.carry is not None:
                return tr.carry
            for req in tr.attempts.values():
                return req
            return None
        for req in self.completed:
            if req.request_id == fid:
                return req
        return None

    def cancel(self, fid) -> bool:
        """Cancel every live attempt of a fleet request (honored at
        each replica's next scheduler turn); a carried replay completes
        with ``RequestCancelled`` at the fleet's next turn."""
        tr = self._reqs.get(fid)
        if tr is None or tr.done is not None:
            return False
        tr.cancelled = True
        for rid, req in list(tr.attempts.items()):
            rep = self.replicas.get(rid)
            if rep is not None and rep.live():
                rep.supervisor.cancel(req.request_id)
        return True

    def has_work(self):
        return bool(self._reqs)     # pending-only by construction

    # ---- the fleet driver ------------------------------------------------

    def step(self):
        """One fleet turn: one supervised scheduler turn per live
        replica, then health checks, drain deadlines, due retries,
        hedge decisions and pending-request reaping. Returns the fleet
        completions produced by this turn."""
        done = []
        for rep in list(self.replicas.values()):
            if not rep.live():
                continue
            try:
                finished = rep.step()
            except (KeyboardInterrupt, SystemExit, AssertionError):
                raise
            except Exception as exc:  # noqa: BLE001 — breaker opens
                done.extend(self._eject(rep, exc, kind="breaker"))
                continue
            for req in finished:
                out = self._absorb(rep, req)
                if out is not None:
                    done.append(out)
            if rep.wedged(self.no_progress_turns):
                done.extend(self._eject(
                    rep,
                    RuntimeError(
                        f"replica {rep.id} wedged: heartbeats without "
                        f"progress for {rep._stale_turns} turns"),
                    kind="wedge"))
                continue
            if rep.state == "draining":
                done.extend(self._check_drain(rep))
        now = time.perf_counter()
        # reap BEFORE firing retries: a carried request that was
        # cancelled or expired while waiting out its backoff must
        # complete with its typed error, never be resurrected onto a
        # sibling (regression-tested)
        done.extend(self._reap_pending(now))
        done.extend(self._fire_retries(now))
        self._check_hedges(now)
        return done

    def run(self):
        """Drive until every submitted request completes; returns the
        completions (exactly one per fleet id) in completion order.
        Armed with the flight-recorder watchdog: a replica step that
        HANGS stops the beats and the recorder dumps a diagnosable
        bundle (the liveness half of the health model)."""
        done = []
        token = _frec.arm("fleet run loop")
        # while the fleet is live, flight-recorder bundles carry the
        # FEDERATED snapshot: a replica-death post-mortem shows every
        # sibling's state at the moment of failure (ISSUE 13)
        rec = _frec.get_recorder()
        prev_fleet_reg = None
        if rec is not None:
            prev_fleet_reg = rec.fleet_registry
            rec.fleet_registry = self.metrics
        t_run = time.perf_counter()
        try:
            while True:
                _frec.beat(token)
                out = self.step()
                done.extend(out)
                if not self.has_work():
                    break
                if not out:
                    # nothing moved this turn: if everything left is
                    # gated on backoff timers, sleep toward the
                    # earliest instead of busy-spinning
                    gates = [tr.not_before
                             for tr in self._reqs.values()
                             if tr.done is None
                             and tr.carry is not None]
                    if gates and not any(
                            r.live() and r.has_work()
                            for r in self.replicas.values()):
                        wait = min(gates) - time.perf_counter()
                        if wait > 0:
                            time.sleep(min(wait, 0.05))
        finally:
            self._run_s += time.perf_counter() - t_run
            if rec is not None:
                rec.fleet_registry = prev_fleet_reg
            _frec.disarm(token)
            self._emit_gauges()
        return done

    # ---- completion plumbing ---------------------------------------------

    def _deliver(self, tr, req):
        tr.done = req
        tr.carry = None
        self._reqs.pop(tr.fid, None)   # pending set stays bounded
        self.completed.append(req)
        self.metrics.counter("fleet/completed").inc()
        # ---- the fleet observability block (self-measured: rides the
        # obs_overhead_frac pin) — SLO booking, the completed-trace
        # log, chrome reconstruction. The delivered object may be a
        # fresh failover attempt, but every attempt shares tr.hops,
        # so the summary carries the WHOLE cross-replica timeline
        _t_obs = time.perf_counter()
        record_hop(req, "deliver", reason=req.finish_reason,
                   retries=tr.retries, hedged=tr.hedged)
        if self.slo is not None:
            try:
                self.slo.record(req)
            except Exception:  # noqa: BLE001 — accounting must never
                pass           # fail a delivery
        get_trace_log().record(request_trace_summary(req))
        self._emit_fleet_trace(tr, req)
        self._obs_s += time.perf_counter() - _t_obs
        _frec.record_event("fleet_finish", fid=tr.fid,
                           reason=req.finish_reason,
                           tokens=len(req.tokens))
        return req

    def _emit_fleet_trace(self, tr, req):
        """Reconstruct the request's cross-replica timeline into the
        chrome trace (Tracer.complete, retroactive): one parent span
        on the trace-id track, one child span per replica ATTEMPT
        (admit → finish/preempt/salvage — the hedge winner and its
        cancelled loser appear as sibling spans of the one trace), and
        zero-length hop markers at their true timestamps."""
        tracer = get_tracer()
        if not tracer.enabled:
            return
        t_end = req.t_done or time.perf_counter()
        tid = int(tr.fid)
        tracer.complete("fleet/request", tr.t_submit, t_end,
                        cat="fleet_req", tid=tid, trace_id=tid,
                        reason=req.finish_reason,
                        tokens=len(req.tokens), tenant=tr.tenant,
                        retries=tr.retries, hedged=tr.hedged)
        open_attempts: dict = {}
        for h in tr.hops:
            kind = h.get("kind")
            rep = h.get("replica")
            if kind == "admit":
                open_attempts.setdefault(rep, h["t"])
            elif kind in ("finish", "preempt", "evict",
                          "engine_restart", "salvage") \
                    and rep in open_attempts:
                tracer.complete(
                    "fleet/attempt", open_attempts.pop(rep), h["t"],
                    cat="fleet_req", tid=tid, replica=rep,
                    outcome=h.get("reason", kind))
            tracer.complete("req/hop", h["t"], h["t"],
                            cat="fleet_req", tid=tid, **h)

    def _absorb(self, rep, req):
        """Fold one replica completion into the fleet view; returns
        the fleet completion to deliver, or None (hedge loser,
        duplicate, or an attempt whose sibling copy still runs)."""
        tr = self._reqs.get(req.request_id)
        if tr is None:
            return None        # warmup internals (id -1) and the like
        was_hedge = tr.hedge_rid == rep.id
        tr.attempts.pop(rep.id, None)
        if tr.done is not None:
            return None        # the losing copy of a decided request
        if req.error is not None and tr.attempts and not tr.cancelled:
            # a failed attempt with a live sibling copy: the sibling
            # decides — this one is discarded, not delivered
            tr.last_error = req.error
            return None
        if req.error is None and tr.attempts:
            # winner: cancel the losing copies (they complete with
            # RequestCancelled on their replicas and are discarded)
            for orid, oreq in list(tr.attempts.items()):
                orep = self.replicas.get(orid)
                if orep is not None and orep.live():
                    orep.supervisor.cancel(oreq.request_id)
                self.metrics.counter("fleet/hedge_cancels").inc()
        if was_hedge and req.error is None:
            self.metrics.counter("fleet/hedge_wins").inc()
        return self._deliver(tr, req)

    # ---- failure handling: breaker, wedge, reroute -----------------------

    def _eject(self, rep, exc, kind):
        """Eject a replica: mark it, salvage its queue + in-flight
        and re-route to siblings. ``kind`` is ``"breaker"`` (restart
        budget spent), ``"wedge"`` (the no-progress health check) or
        ``"operator"`` (an explicit :meth:`eject` — no failure
        counter, and the reroute does not burn retry budget)."""
        rep.state = "ejected"
        rep.eject_kind = kind
        if kind == "wedge":
            self.metrics.counter("fleet/wedge_ejections").inc()
        elif kind == "breaker":
            self.metrics.counter("fleet/breaker_open").inc()
        _frec.record_event("fleet_eject", replica=rep.id, cause=kind,
                           error=repr(exc)[:200])
        salvage = salvage_unfinished(rep.engine)
        rep.on_eject(kind)   # after salvage: the shadow was the source
        return self._reroute(salvage, rep, exc,
                             count_retry=kind != "operator")

    def _reroute(self, reqs, rep, cause, count_retry=True):
        """Schedule salvaged requests for replay on siblings (backoff
        + jitter when ``count_retry``; immediate for drain evictions).
        Returns the completions produced when a retry budget is
        already spent."""
        now = time.perf_counter()
        done, n = [], 0
        for req in reqs:
            tr = self._reqs.get(req.request_id)
            if tr is None or req.finished:
                continue
            tr.attempts.pop(rep.id, None)
            if tr.done is not None:
                continue   # losing hedge copy dies with its replica
            if tr.attempts:
                continue   # a live sibling copy still runs
            n += 1
            record_hop(req, "salvage", replica=rep.id,
                       tokens=len(req.tokens))
            if count_retry:
                tr.retries += 1
                if tr.retries > self.max_retries:
                    done.append(self._finish_failed(tr, req, cause))
                    continue
                self.metrics.counter("fleet/retries").inc()
                tr.carry = req
                tr.not_before = now + self._backoff_s(tr.retries)
            else:
                tr.carry = req
                tr.not_before = now
            tr.t_failed = now   # failover clock: observed at
        self.metrics.counter("fleet/requeued").inc(n)   # re-admission
        return done

    def _backoff_s(self, attempt, floor_s=0.0):
        """Exponential backoff with jitter:
        ``base * 2^(attempt-1)``, jittered ±``retry_jitter``, capped —
        then FLOORED by any fleet-wide ``Overloaded.retry_after_s``
        (the router's computed estimate outranks the blind schedule)."""
        b = self.retry_backoff_s * (2 ** max(0, attempt - 1))
        b *= 1.0 + self.retry_jitter * (2 * self._rng.random() - 1.0)
        return max(floor_s, min(self.retry_backoff_cap_s, b))

    def _finish_failed(self, tr, req, cause):
        req.finished = True
        req.error = ReplicaFailed(tr.fid, cause=repr(cause)[:200])
        req.finish_reason = "failed"
        req.t_done = time.perf_counter()
        record_hop(req, "failed", retries=tr.retries,
                   cause=repr(cause)[:80])
        return self._deliver(tr, req)

    def _fire_retries(self, now):
        done = []
        fleet_alive = any(r.state in ("ready", "warming")
                          for r in self.replicas.values())
        for tr in list(self._reqs.values()):
            if tr.done is not None or tr.carry is None:
                continue
            if tr.cancelled:
                continue       # the reap owns it (typed completion)
            if not fleet_alive:
                # nothing will ever take this request again: typed
                # failure, never a silent hang
                done.append(self._finish_failed(
                    tr, tr.carry,
                    RuntimeError("no replica left in the fleet")))
                continue
            if now < tr.not_before:
                continue
            req = tr.carry
            try:
                self._assign(tr, req)
            except Overloaded as exc:
                # the computed retry-after is the backoff FLOOR; an
                # admission shed does not burn the retry budget
                record_hop(req, "shed",
                           retry_after_s=round(exc.retry_after_s, 4))
                tr.not_before = now + self._backoff_s(
                    tr.retries, floor_s=exc.retry_after_s)
                continue
            tr.carry = None
            if tr.t_failed:
                # the failover the client actually experienced:
                # ejection -> re-admission, backoff included
                self._h_failover.observe(
                    (time.perf_counter() - tr.t_failed) * 1e3)
                tr.t_failed = 0.0
        return done

    # ---- hedging ---------------------------------------------------------

    def _hedge_delay(self):
        """The straggler threshold: an explicit ``hedge_delay_s``, or
        ``hedge_factor`` x the BEST ready replica's observed ttft p99
        (the best — a straggler must not inflate its own threshold).
        None while no replica has latency history: with nothing to
        compare against, nobody is a straggler."""
        if self.hedge_delay_s is not None:
            return float(self.hedge_delay_s)
        p99s = [p for rep in self.replicas.values()
                if rep.takes_weight()
                and (p := rep.ttft_p99_s()) is not None]
        if not p99s:
            return None
        return max(self.hedge_min_delay_s,
                   self.hedge_factor * min(p99s))

    def _check_hedges(self, now):
        delay = self._hedge_delay()
        if delay is None:
            return
        for tr in self._reqs.values():
            if tr.done is not None or tr.hedged \
                    or tr.carry is not None or tr.cancelled:
                continue
            if len(tr.attempts) != 1:
                continue
            (rid, req), = tr.attempts.items()
            if req.t_first or req.tokens:
                continue       # first token landed: not a straggler
            if now - tr.t_assign < delay:
                continue
            copy = self._make_attempt(tr)
            try:
                nrid = self._assign(tr, copy, exclude=(rid,))
            except Overloaded:
                continue       # no sibling has room: the straggler
            tr.hedged = True   # keeps the request (one hedge max)
            tr.hedge_rid = nrid
            record_hop(copy, "hedge", replica=nrid, straggler=rid)
            self.metrics.counter("fleet/hedges").inc()
            _frec.record_event(
                "fleet_hedge", fid=tr.fid, straggler=rid,
                sibling=nrid,
                waited_ms=round((now - tr.t_assign) * 1e3, 2))

    # ---- pending reap ----------------------------------------------------

    def _reap_pending(self, now):
        """Lifecycle control for requests the FLEET is holding (backoff
        gate between assignments): cancellations and deadline expiries
        complete with typed errors instead of waiting forever."""
        done = []
        for tr in list(self._reqs.values()):   # _deliver pops entries
            if tr.done is not None or tr.carry is None:
                continue
            req = tr.carry
            err = None
            if tr.cancelled:
                err = RequestCancelled(tr.fid)
                req.finish_reason = "cancelled"
            elif tr.deadline_s is not None \
                    and now - tr.t_submit > tr.deadline_s:
                err = DeadlineExceeded(tr.fid, "total", tr.deadline_s)
                req.finish_reason = "deadline"
            elif tr.ttft_deadline_s is not None and not req.t_first \
                    and now - tr.t_submit > tr.ttft_deadline_s:
                err = DeadlineExceeded(tr.fid, "ttft",
                                       tr.ttft_deadline_s)
                req.finish_reason = "deadline"
            if err is None:
                continue
            req.finished = True
            req.error = err
            req.t_done = now
            done.append(self._deliver(tr, req))
        return done

    # ---- elasticity ------------------------------------------------------

    def scale_down(self, replica_id=None, deadline_s=None):
        """Begin a graceful drain: admission stops immediately (the
        router drops the replica's weight), in-flight requests keep
        running until done or until ``deadline_s`` (default
        ``drain_deadline_s``) expires — stragglers are then evicted
        through the engine's ``handoff()`` hook and recomputed on
        siblings. Returns the replica id chosen (least-loaded ready
        replica when not given)."""
        if replica_id is None:
            cands = [r for r in self.replicas.values()
                     if r.state == "ready"]
            if not cands:
                raise ValueError("no ready replica to drain")
            rep = min(cands, key=lambda r: (r.load(), r.id))
        else:
            rep = self.replicas[replica_id]
            if rep.state != "ready":
                raise ValueError(
                    f"replica {replica_id} is {rep.state}, not ready")
        rep.state = "draining"
        dl = self.drain_deadline_s if deadline_s is None \
            else float(deadline_s)
        rep.drain_deadline = time.perf_counter() + dl
        _frec.record_event("fleet_drain_begin", replica=rep.id,
                           deadline_s=round(dl, 3))
        return rep.id

    def _check_drain(self, rep):
        done = []
        if not rep.has_work():
            rep.state = "retired"
            self.metrics.counter("fleet/drains").inc()
            _frec.record_event("fleet_drain_done", replica=rep.id,
                               evicted=0)
            rep.close()
        elif rep.drain_deadline is not None \
                and time.perf_counter() >= rep.drain_deadline:
            stragglers = rep.engine.handoff()
            rep.state = "retired"
            self.metrics.counter("fleet/drains").inc()
            _frec.record_event("fleet_drain_done", replica=rep.id,
                               evicted=len(stragglers))
            rep.close()
            done.extend(self._reroute(
                stragglers, rep,
                RuntimeError("drain deadline"), count_retry=False))
        return done

    def scale_up(self, engine_factory=None, warm=True):
        """Register a new replica. With ``warm`` (default) it is
        WARMED before taking router weight: a sacrificial request
        compiles its programs, then its gauges are reset so warmup
        latencies cannot pollute the routing signal. Returns the new
        replica id."""
        # federation waits until AFTER warmup: a concurrent scrape
        # landing between the sacrificial request and reset_gauges()
        # would otherwise record the warmup counters into the
        # federation watermark, and the reset would bank them into the
        # fleet totals forever (scrape-timing-dependent totals)
        rep = self._add_replica(engine_factory or self._factory,
                                federate=False)
        if warm:
            rep.state = "warming"
            self._warm(rep)
        self._federate(rep)
        rep.state = "ready"
        self.metrics.counter("fleet/scale_ups").inc()
        _frec.record_event("fleet_scale_up", replica=rep.id,
                           warmed=bool(warm))
        return rep.id

    def _warm(self, rep):
        eng = rep.engine
        # enough decode budget for several scheduler turns: the first
        # call is the eager discovery trace, the XLA compile itself
        # fires on the first COMPILED run — a one-turn warmup would
        # leave the compile inside the serving path
        n_new = max(2, min(3 * eng.decode_chunk,
                           eng.max_len - 5))
        # id -1: outside the fleet id space, so its completion can
        # never be confused with a client request
        wreq = ServedRequest(-1, np.zeros((4,), np.int32), n_new, None)
        wreq.t_arrive = time.perf_counter()
        eng.requeue(wreq)
        for _ in range(512):
            if not rep.has_work():
                break
            rep.step()
        eng.reset_gauges()

    def close(self):
        """Release every replica's external resources (a process-
        backed replica reaps its worker here); results and gauges
        remain readable — only the replicas' backends are gone."""
        for rep in self.replicas.values():
            rep.close()

    def eject(self, replica_id, reason="operator"):
        """Operator-initiated immediate ejection (no drain): the
        replica's queue + in-flight fail over to siblings right away —
        without counting a breaker trip or burning the salvaged
        requests' retry budget (an operator action is not a failure)."""
        rep = self.replicas[replica_id]
        if not rep.live():
            return []
        return self._eject(rep, RuntimeError(f"ejected: {reason}"),
                           kind="operator")

    # ---- observability ---------------------------------------------------

    def gauges(self) -> dict:
        """Fleet observability surface: the router/health/failover
        economics plus per-replica states."""
        ready = sum(1 for r in self.replicas.values()
                    if r.takes_weight())
        self.metrics.gauge("fleet/replicas_ready").set(ready)

        def c(name):
            return self.metrics.counter(name).value

        return {
            "replicas": len(self.replicas),
            "replicas_ready": ready,
            "replica_states": {r.id: r.state
                               for r in self.replicas.values()},
            "submitted": c("fleet/submitted"),
            "completed": c("fleet/completed"),
            "shed_rejections": c("fleet/shed_rejections"),
            "retries": c("fleet/retries"),
            "requeued": c("fleet/requeued"),
            "hedges": c("fleet/hedges"),
            "affinity_hits": c("fleet/affinity_hits"),
            "hedge_wins": c("fleet/hedge_wins"),
            "hedge_cancels": c("fleet/hedge_cancels"),
            "breaker_open": c("fleet/breaker_open"),
            "wedge_ejections": c("fleet/wedge_ejections"),
            "drains": c("fleet/drains"),
            "scale_ups": c("fleet/scale_ups"),
            "queue_depth": sum(r.queue_depth()
                               for r in self.replicas.values()
                               if r.live()),
            "shed_rate": round(sum(r.shed_rate()
                                   for r in self.replicas.values()
                                   if r.live()), 4),
            "failover_ms_p99": self._h_failover.percentile(99),
            "obs_overhead_frac": (self._obs_s / self._run_s)
            if self._run_s else 0.0,
        }

    def _emit_gauges(self):
        self.metrics.gauge("fleet/replicas_ready").set(
            sum(1 for r in self.replicas.values()
                if r.takes_weight()))
        self.metrics.gauge("obs/overhead_frac").set(
            (self._obs_s / self._run_s) if self._run_s else 0.0)
        # ISSUE 19: the pressure signals, fleet-level AND mirrored
        # onto each live replica's own registry (labeled children on
        # the federated scrape) — the router, the autoscaler and
        # /statusz all read the same numbers
        q_total = s_total = 0.0
        for r in self.replicas.values():
            if not r.live():
                continue
            try:
                q, s = r.queue_depth(), r.shed_rate()
            except Exception:  # noqa: BLE001 — a replica mid-teardown
                continue       # must not tear the gauge sweep
            q_total += q
            s_total += s
            r.engine.metrics.gauge("serving/queue_depth").set(q)
            r.engine.metrics.gauge("serving/shed_rate").set(s)
        self.metrics.gauge("fleet/queue_depth").set(q_total)
        self.metrics.gauge("fleet/shed_rate").set(round(s_total, 4))

    # ---- /statusz + exposition (ISSUE 13) --------------------------------

    def _statusz_replicas(self):
        """Per-replica health: state, breaker/eject cause, supervisor
        restarts, load + latency signal, prefix-cache hit rate — the
        fleet-operator view of the PR-11 health model."""
        out = {}
        for r in self.replicas.values():
            entry = {"state": r.state, "eject_kind": r.eject_kind,
                     "restarts": r.supervisor.restarts,
                     "breaker_open": r.eject_kind == "breaker",
                     "stale_turns": r._stale_turns}
            try:
                p99 = r.ttft_p99_s()
                g = r.supervisor.gauges()
                entry.update(
                    load=round(r.load(), 4),
                    queued=r.queue_depth(),
                    shed_rate=round(r.shed_rate(), 4),
                    ttft_p99_ms=round(p99 * 1e3, 3)
                    if p99 is not None else None,
                    tokens_emitted=g.get("tokens_emitted", 0),
                    requests_completed=g.get("requests_completed", 0),
                    prefix_cache_hit_rate=round(
                        g.get("prefix_cache_hit_rate", 0.0), 4),
                    preempt_evictions=g.get("preempt_evictions", 0))
            except Exception as exc:  # noqa: BLE001 — a replica mid-
                # teardown must not tear the whole health render
                entry["error"] = f"{type(exc).__name__}: {exc}"
            out[str(r.id)] = entry
        return out

    def _statusz_traces(self, n=10):
        """The N slowest recent end-to-end request traces."""
        return get_trace_log().slowest(n)

    def statusz_sections(self) -> dict:
        """The named /statusz section providers (each a zero-arg
        callable, evaluated per scrape and individually guarded by the
        ObservabilityServer): fleet router economics, per-replica
        health/breaker state, SLO attainment + burn-rate alerts, the
        slowest recent traces, flight-recorder incidents, and the
        current goodput summary (the most recent fit run's ledger,
        when one exists in this process)."""
        from ..profiler import goodput as _goodput

        def _slo():
            return self.slo.summary() if self.slo is not None else None

        def _goodput_section():
            ledger = _goodput.get_current()
            return ledger.summary() if ledger is not None else None

        def _flight():
            rec = _frec.get_recorder()
            if rec is None:
                return None
            return {"dumps": rec.dumps,
                    "last_bundle": rec.last_bundle_path,
                    "incidents": rec.incidents()}

        def _autoscaler():
            # attached by FleetAutoscaler's ctor (ISSUE 19): the
            # structured decision log — signals in, rule fired, action
            # out; None for an operator-scaled fleet
            ctl = getattr(self, "autoscaler", None)
            return ctl.statusz() if ctl is not None else None

        return {
            "fleet": self.gauges,
            "replicas": self._statusz_replicas,
            "slo": _slo,
            "autoscaler": _autoscaler,
            "slowest_traces": self._statusz_traces,
            "flight_recorder": _flight,
            "goodput": _goodput_section,
        }

    def statusz(self) -> dict:
        """The /statusz document as a dict — the SAME guarded
        evaluation the HTTP render uses (one loop, cannot drift)."""
        from ..profiler.exposition import evaluate_sections
        return evaluate_sections(self.statusz_sections())

    def observability_server(self, host="127.0.0.1", port=0,
                             start=True):
        """The fleet's operational front door: an
        :class:`~paddle_tpu.profiler.exposition.ObservabilityServer`
        wired to the federated registry (``/metrics``) and the statusz
        sections (``/statusz``). ``port=0`` binds an ephemeral port;
        the caller owns ``stop()``."""
        from ..profiler.exposition import ObservabilityServer
        srv = ObservabilityServer(
            registry=self.metrics, sections=self.statusz_sections(),
            host=host, port=port,
            # /metrics-only scrapers must read CURRENT slo gauges —
            # a tenant gone silent after a bad minute self-resolves
            # on the scrape path too, not just /statusz
            pre_scrape=(self.slo.refresh if self.slo is not None
                        else None))
        return srv.start() if start else srv
