"""Continuous-batching LLM serving engine over paged KV caches.

Reference role: the serving layer PaddleNLP/FastDeploy put on top of
Paddle Inference (dynamic batching + paged/ragged KV attention for mixed-
length streams; reference mount empty, no cites — SURVEY.md §2.1
inference row, PAPERS.md ragged-paged-attention).

TPU-native design — the vLLM recipe restructured for XLA's static-shape
world. Two engine modes share the pool/slot machinery:

**Unified mode (default, ``unified=True``)** — ONE compiled
batching-step program for the whole scheduler turn, built on the ragged
paged-attention entry point (PAPERS.md "Ragged Paged Attention"): a
mixed ragged pass advances every slot — prefill slots stream their next
``prefill_chunk`` prompt tokens, active decode slots ride their pending
token as a length-1 sequence, idle slots are length 0 — through one
``[num_slots, prefill_chunk]`` forward, samples where a prompt
completes or a decode step fires, then chains ``decode_chunk - 1``
in-program decode micro-steps via ``lax.scan``. Prefill→decode
transition happens ON DEVICE inside the program (a slot whose prompt
ends in the mixed pass decodes from micro-step 1), so the PR-3
prefill-wave/decode-chunk interleave, its first-token echo machinery,
and the residual compiled-signature zoo all collapse: steady-state
``compiled_programs`` == 1.

**Legacy mode (``unified=False``)** — the PR-3 two-program-family
engine (batched prefill waves interleaved with adaptive decode chunks),
kept as the scheduling-parity oracle for the ``serving_parity`` CI gate
and for A/B benching.

Shared structure:

- The KV cache is a global PAGE POOL per layer ([KVH, num_pages,
  page_size, D]); each admitted request owns a page list (its block
  table row). Page 0 is a reserved trash page for drained slots.
- A fixed number of SLOTS (the batch dimension) keeps every compiled
  shape static. Admission = host-side: allocate pages from the free
  list and mark the slot PREFILLING.
- Prefill is CHUNKED and BATCHED through the paged pool: ONE compiled
  prefill signature ([num_slots, prefill_chunk] ids) advances every
  prefilling slot ``prefill_chunk`` prompt tokens per program — k/v are
  written into the slot's pages incrementally
  (``ops.paged_attention.paged_prefill_write``) and the chunk's queries
  attend causally over the paged history
  (``paged_prefill_attention``). No per-bucket dense-cache forward, no
  exact-length recompiles for prompts longer than every bucket: every
  prompt length flows through the same program, and up to
  ``admit_batch`` queued prompts ride one program together. Prefill
  waves INTERLEAVE with decode chunks, so a long prompt no longer
  stalls active decode streams.
- Decoding runs in compiled CHUNKS: ONE program advances ALL active
  slots ``n`` tokens via a ``lax.scan`` (per-slot positions, paged
  attention reads, trash-page-guarded writes). The chunk length is
  ADAPTIVE (``adaptive_chunk``): clamped to the minimum remaining token
  budget across active slots (quantized to a power-of-two ladder under
  ``decode_chunk`` to bound compiled signatures), so a drain wave ends
  exactly at the chunk boundary — no overshoot slot-steps, and the
  once-per-drain-wave wasted speculative chunk program is gone (the
  host can prove the successor would do no work).
- Between chunks the host scheduler drains finished slots (eos or token
  budget), frees their pages, and admits queued requests into the freed
  slots — mixed-length streams flow through without ever reshaping the
  compiled programs.
- Hot state (last token / context length / active mask / RNG key / page
  pools) is DEVICE-RESIDENT between programs: prefill waves and decode
  chunks chain device state asynchronously; each decode chunk fetches
  one packed int32 array (emitted tokens + first-token echoes + ctx/
  active mirrors), and prefill never fetches — a prompt's first token
  lands in device state and is echoed through the next chunk's packed
  fetch. Measured on the tunnel (v5e): per-call overhead was ~0.5s with
  per-array uploads + a blocking scalar fetch per admission; round
  trips, not kernels, set the serving throughput.
- Per-request latency accounting rides the scheduler: TTFT (arrival →
  first token on host) and smoothed inter-token latency, exposed as
  p50/p99 gauges next to the occupancy/overlap counters from PR 2, plus
  a compiled-signature counter (``compiled_programs``) that the
  compile-budget CI gate asserts on.
"""

from __future__ import annotations

import time
from collections import deque
from dataclasses import dataclass, field

import jax
import jax.numpy as jnp
import numpy as np

from ..framework.core import Tensor, no_grad
from ..profiler import flight_recorder as _frec
from ..profiler import metrics as _pmetrics

__all__ = ["ContinuousBatchingEngine", "ServedRequest"]

# the serving metric vocabulary (docs/observability.md table;
# tools/check_metric_names.py lints these literals). Each engine owns
# a PRIVATE MetricsRegistry instance of these — two engines in one
# process never cross-pollute.
_pmetrics.declare("serving/chunks", "counter",
                  "compiled programs dispatched (unified steps + legacy "
                  "decode chunks)")
_pmetrics.declare("serving/chunk_slot_steps", "counter",
                  "slot-steps dispatched (num_slots x chunk length, "
                  "active or not)")
_pmetrics.declare("serving/active_slot_steps", "counter",
                  "slot-steps belonging to slots that could advance at "
                  "dispatch")
_pmetrics.declare("serving/tokens_emitted", "counter",
                  "generated tokens delivered to requests")
_pmetrics.declare("serving/prefills", "counter",
                  "requests admitted into a slot")
_pmetrics.declare("serving/prefills_overlapped", "counter",
                  "admissions made while a compiled program was in "
                  "flight (overlap pipeline)")
_pmetrics.declare("serving/prefill_waves", "counter",
                  "programs that carried prompt tokens")
_pmetrics.declare("serving/chunks_empty", "counter",
                  "harvested programs that delivered no tokens "
                  "(unpredictable eos stops)")
_pmetrics.declare("serving/unified_steps", "counter",
                  "unified batching-step programs dispatched (0 in "
                  "legacy mode)")
_pmetrics.declare("serving/requests_completed", "counter",
                  "requests finished (eos or length)")
_pmetrics.declare("serving/run_seconds", "counter",
                  "wall seconds spent inside run()")
_pmetrics.declare("serving/ttft_ms", "histogram",
                  "request arrival -> first token on host, ms (bounded "
                  "reservoir; p50/p99 exposed via gauges())")
_pmetrics.declare("serving/itl_ms", "histogram",
                  "smoothed inter-token latency per request with >=2 "
                  "tokens, ms (bounded reservoir)")
_pmetrics.declare("obs/overhead_frac", "gauge",
                  "fraction of serving run() wall time spent inside "
                  "observability instrumentation (self-measured; the "
                  "<2% pinned contract)")

#: the historical ``_stats`` key set, preserved verbatim — now backed
#: by ``serving/*`` registry counters
_STAT_KEYS = ("chunks", "chunk_slot_steps", "active_slot_steps",
              "tokens_emitted", "prefills", "prefills_overlapped",
              "prefill_waves", "chunks_empty", "unified_steps",
              "requests_completed", "run_seconds")


class _StatsView:
    """Dict-shaped view over the engine's registry counters: the
    ``_stats`` surface predates the metrics registry and tests index
    it (``eng._stats["active_slot_steps"]``), so the migration keeps
    the mapping protocol while the registry holds the truth."""

    __slots__ = ("_c",)

    def __init__(self, registry):
        self._c = {k: registry.counter("serving/" + k)
                   for k in _STAT_KEYS}

    def __getitem__(self, k):
        return self._c[k].value

    def __setitem__(self, k, v):
        self._c[k].set(v)

    def inc(self, k, n=1):
        self._c[k].inc(n)

    def __iter__(self):
        return iter(self._c)

    def keys(self):
        return self._c.keys()

    def as_dict(self):
        return {k: c.value for k, c in self._c.items()}


@dataclass
class ServedRequest:
    request_id: int
    prompt: np.ndarray                 # [S] int
    max_new_tokens: int
    eos_token_id: int | None = None
    tokens: list = field(default_factory=list)   # generated ids
    finished: bool = False
    finish_reason: str | None = None   # "eos" | "length"
    # latency accounting (seconds, perf_counter clock)
    t_arrive: float = 0.0              # add_request
    t_admit: float = 0.0               # admitted into a slot
    t_prefill_done: float = 0.0        # prompt fully streamed
    t_first: float = 0.0               # first token visible host-side
    t_done: float = 0.0                # finished
    #: lifecycle-trace sampling decision (engine trace_sample_rate)
    traced: bool = False


class ContinuousBatchingEngine:
    """Schedules mixed-length generation streams through ONE compiled
    unified batching-step program (ragged mixed prefill+decode; default)
    or, with ``unified=False``, the legacy prefill-wave/decode-chunk
    pair. Greedy or temperature sampling.

    model: any CausalLM Layer implementing ``forward(ids, caches=, pos=,
    tables=)`` + ``init_kv_cache`` — Llama, Qwen2 (incl. MoE), and GPT2
    all qualify. num_slots is the batch size; total pool memory =
    num_pages * page_size tokens of KV per layer.

    ``prompt_buckets`` is kept for API compatibility: buckets no longer
    select prefill signatures (there is exactly ONE), but the largest
    bucket seeds the default ``prefill_chunk``."""

    def __init__(self, model, num_slots=4, page_size=16, num_pages=None,
                 max_len=512, decode_chunk=None, prompt_buckets=(32, 64, 128),
                 eos_token_id=None, greedy=True, temperature=1.0,
                 seed=0, prefill_chunk=None, admit_batch=None,
                 adaptive_chunk=True, unified=True,
                 trace_sample_rate=0.01, latency_reservoir=2048):
        self.model = model
        cfg = model.config
        self.cfg = cfg
        self.num_slots = int(num_slots)
        self.page_size = int(page_size)
        self.max_len = int(max_len)
        self.pages_per_slot = -(-self.max_len // self.page_size)
        # +1: page 0 is the reserved trash page
        self.num_pages = int(num_pages) if num_pages is not None else \
            self.num_slots * self.pages_per_slot + 1
        # also the KV-pool dtype below AND the tuner-cache key's dtype
        # component — one probe so the two can never diverge
        dtype = next(iter(model.parameters()))._data.dtype
        # chunk-ladder knobs left as None resolve through the autotuner
        # cache ("serving_chunks" surface, keyed by slots/max_len/page —
        # registered at the bottom of this module), then fall back to
        # the static derivations; an explicit argument always wins
        tuned = {}
        if decode_chunk is None or prefill_chunk is None \
                or admit_batch is None:
            from ..tuner import lookup
            tuned = lookup("serving_chunks",
                           {"slots": self.num_slots,
                            "max_len": self.max_len,
                            "page": self.page_size}, str(dtype)) or {}
        if decode_chunk is None:
            decode_chunk = int(tuned.get("decode_chunk", 0)) or 16
        self.decode_chunk = int(decode_chunk)
        self.adaptive_chunk = bool(adaptive_chunk)
        self.prompt_buckets = tuple(sorted(prompt_buckets)) \
            if prompt_buckets else ()
        if prefill_chunk is None:
            prefill_chunk = int(tuned.get("prefill_chunk", 0)) or \
                (self.prompt_buckets[-1] if self.prompt_buckets else 32)
        self.prefill_chunk = max(1, min(int(prefill_chunk), self.max_len))
        if admit_batch is None:
            admit_batch = int(tuned.get("admit_batch", 0)) or self.num_slots
        self.admit_batch = max(1, min(int(admit_batch), self.num_slots))
        self.eos = -1 if eos_token_id is None else int(eos_token_id)
        self.greedy = bool(greedy)
        self.temperature = float(temperature)

        # MHA models (e.g. GPT2) carry no kv-head/head-dim fields
        kvh = getattr(cfg, "num_key_value_heads",
                      cfg.num_attention_heads)
        d = getattr(cfg, "head_dim",
                    cfg.hidden_size // cfg.num_attention_heads)
        # per layer: (key_pages, value_pages) — flat list like dense caches
        self.pools = []
        for _ in range(cfg.num_hidden_layers):
            for _kv in range(2):
                self.pools.append(Tensor(jnp.zeros(
                    (kvh, self.num_pages, self.page_size, d), dtype)))

        self._free_pages = deque(range(1, self.num_pages))
        # host-side slot bookkeeping (admission decisions, drain)
        B, MP = self.num_slots, self.pages_per_slot
        self.tables = np.zeros((B, MP), np.int32)
        self.ctx = np.zeros((B,), np.int32)       # mirror (packed fetch)
        self.active = np.zeros((B,), bool)        # mirror (packed fetch)
        self.limits = np.zeros((B,), np.int32)    # ctx budget per slot
        self.slot_eos = np.full((B,), -1, np.int32)  # per-request eos
        self.slot_req: list[ServedRequest | None] = [None] * B
        self.slot_pages: list[list] = [[] for _ in range(B)]
        # chunked-prefill progress: a slot whose prompt is still being
        # streamed into its pages is PREFILLING — inactive for decode,
        # ineligible for drain
        self._prefilling = np.zeros((B,), bool)
        self._prefill_off = np.zeros((B,), np.int32)   # tokens dispatched
        self._act_target = np.zeros((B,), bool)  # activate on completion
        # host prediction of device ctx (exact for length-limited slots;
        # an eos stop only ever makes it an overestimate) — drives the
        # adaptive chunk length and the is-the-successor-worth-it test
        self._pred_ctx = np.zeros((B,), np.int32)
        # monotone program-dispatch counter + per-slot activation seq:
        # a decode chunk dispatched BEFORE a slot's final prefill wave
        # has a stale view of that slot, so its ctx/active mirrors must
        # not be applied at harvest
        self._seq = 0
        self._act_since = np.zeros((B,), np.int64)
        # pending first-token echo: slots whose prefill finished but
        # whose first token has not been appended host-side yet
        self._pending_first = np.zeros((B,), bool)
        # echo snapshotted into a dispatched-but-unharvested chunk: the
        # slot must not drain until that harvest appends the token (a
        # one-shot request admitted mid-stream would otherwise finish
        # empty — its pending flag is cleared at dispatch, but the token
        # only arrives with the chunk's packed fetch)
        self._echo_inflight = np.zeros((B,), bool)

        # device-resident hot state (never round-trips between chunks);
        # admission mutates it with tiny async .at[slot].set dispatches
        self._dev_tok = jnp.zeros((B,), jnp.int32)
        self._dev_ctx = jnp.zeros((B,), jnp.int32)
        self._dev_act = jnp.zeros((B,), bool)
        self._dev_tbl = jnp.zeros((B, MP), jnp.int32)
        self._dev_lim = jnp.zeros((B,), jnp.int32)
        self._dev_eos = jnp.full((B,), -1, jnp.int32)

        self.queue: deque[ServedRequest] = deque()
        self.completed: list[ServedRequest] = []
        self._next_id = 0
        self._key = jax.random.PRNGKey(seed)
        self._prefill_fn = None        # legacy: ONE prefill signature
        self._chunk_fns = {}           # legacy: chunk len -> program
        self._compiled = set()         # distinct compiled signatures
        # unified mode: ONE batching-step program (mixed ragged pass +
        # decode_chunk-1 in-program decode micro-steps); per-slot count
        # of dispatched-but-unharvested steps that may emit tokens for
        # the slot — drain defers while any are in flight
        self._unified = bool(unified)
        self._n_decode = max(0, self.decode_chunk - 1)
        self._unified_fn = None
        self._emits_inflight = np.zeros((B,), np.int32)

        # perf observability (profiler subsystem): a PRIVATE typed
        # metrics registry behind the :meth:`gauges` surface — slot
        # occupancy, admission/prefill overlap, tok/s, latency
        # percentiles. Counters maintained unconditionally; latency
        # samples live in BOUNDED reservoirs (a long-lived engine's
        # memory stays flat over millions of completions — the lists
        # this replaces grew without limit); mirrored into the trace
        # layer only when tracing is enabled.
        self.metrics = _pmetrics.MetricsRegistry()
        self._stats = _StatsView(self.metrics)
        self._h_ttft = self.metrics.histogram(
            "serving/ttft_ms", capacity=int(latency_reservoir))
        self._h_itl = self.metrics.histogram(
            "serving/itl_ms", capacity=int(latency_reservoir))
        self._g_overhead = self.metrics.gauge("obs/overhead_frac")
        # observability self-measurement: seconds spent inside
        # instrumentation on the hot path (gauges()["obs_overhead_frac"]
        # = _obs_s / run_seconds; pinned < 2% by test)
        self._obs_s = 0.0
        # per-request lifecycle tracing: every Nth request (by id) gets
        # its spans reconstructed into the chrome trace at completion —
        # hot-path cost for a traced request is a few float stamps
        self._trace_every = int(round(1.0 / trace_sample_rate)) \
            if trace_sample_rate and trace_sample_rate > 0 else 0
        self._overlap_admission = False

    # ---- public API ------------------------------------------------------

    def add_request(self, prompt_ids, max_new_tokens,
                    eos_token_id=None) -> int:
        prompt = np.asarray(prompt_ids, np.int32).reshape(-1)
        if prompt.size + int(max_new_tokens) > self.max_len:
            raise ValueError(
                f"prompt ({prompt.size}) + max_new_tokens "
                f"({max_new_tokens}) exceeds engine max_len {self.max_len}")
        # reject what the pool can NEVER satisfy — otherwise run() would
        # spin forever waiting for pages that cannot exist
        need = -(-(prompt.size + int(max_new_tokens)) // self.page_size)
        if need > self.num_pages - 1:
            raise ValueError(
                f"request needs {need} pages but the pool only has "
                f"{self.num_pages - 1} allocatable")
        req = ServedRequest(self._next_id, prompt, int(max_new_tokens),
                            eos_token_id if eos_token_id is not None
                            else (self.eos if self.eos >= 0 else None))
        req.t_arrive = time.perf_counter()
        self._next_id += 1
        self.queue.append(req)
        return req.request_id

    def has_work(self) -> bool:
        return bool(self.queue) or bool(self.active.any()) \
            or bool(self._prefilling.any())

    def step(self):
        """Admit what fits, advance every slot one scheduler turn (one
        unified batching-step program, or prefill waves + one decode
        chunk in legacy mode), drain finished slots. Returns the
        requests completed by this step."""
        self._admit()
        if self._unified:
            if self._worth_step():
                self._harvest_step(self._dispatch_step())
            return self._drain()
        self._pump_prefill()
        if self.active.any():
            self._decode_chunk()
        return self._drain()

    def run(self):
        """Drive until every queued request completes; returns them in
        completion order.

        Pipelined: the NEXT chunk is ALWAYS dispatched before the
        previous chunk's packed output is fetched — device state chains
        asynchronously, so the harvest round-trip AND the whole
        admission wave (prefill-chunk programs, slot-state updates)
        execute while the speculative successor decodes on device: a
        prefill wave consumes the successor's output pools, so it simply
        joins the device stream after it, and an admitted slot starts
        decoding in the chunk after its final prefill wave. A slot that
        finished inside the previous chunk is inactive in the
        speculative successor (its device active flag is already False),
        so the overlap never decodes garbage. The successor is SKIPPED
        when the host can prove it would do no work (every active slot's
        predicted remaining budget is zero) — with adaptive chunk
        lengths that proof fires exactly at each drain wave, so the
        round-4 "one wasted chunk program per drain wave" cost is gone
        (``chunks_empty`` measures any residue, e.g. eos stops the host
        cannot predict).

        Unified mode runs the SAME driver with its own hooks: the
        speculative successor is a whole batching-step program, there
        is no separate prefill pump (prompt streaming, activation, the
        first-token sample and the decode tail all live inside the
        step), and the successor is skipped when no prefilling slot
        exists and every active slot's predicted budget is exhausted."""
        if self._unified:
            return self._run_driver(
                spec_dispatch=lambda: self._dispatch_step()
                if self._worth_step() else None,
                harvest=self._harvest_step,
                after_admit=lambda: None,
                idle_turn=self._idle_turn_unified)
        return self._run_driver(
            spec_dispatch=lambda: self._dispatch_chunk()
            if self._worth_dispatching() else None,
            harvest=self._harvest_chunk,
            # ONE prefill wave per scheduler turn: prompt streaming
            # interleaves with decode chunks instead of stalling them
            after_admit=lambda: self._pump_prefill(max_waves=1),
            idle_turn=self._idle_turn_legacy)

    def _idle_turn_unified(self):
        """Nothing in flight: dispatch a step if it would advance
        anything. Returns (progressed, inflight record or None)."""
        if self._worth_step():
            return True, self._dispatch_step()
        return False, None

    def _idle_turn_legacy(self):
        """Nothing in flight: stream one prefill wave if prompts are
        pending, else dispatch a decode chunk if slots are active."""
        if self._prefilling.any():
            self._pump_prefill(max_waves=1)
            return True, None
        if self.active.any():
            return True, self._dispatch_chunk()
        return False, None

    def _run_driver(self, spec_dispatch, harvest, after_admit,
                    idle_turn):
        """The one scheduler loop both modes share — hooks differ, the
        pipelining skeleton, overlap-admission accounting and stall
        detection must not (a fix here fixes both engines)."""
        done = []
        inflight = None
        t_run0 = time.perf_counter()
        _wd_token = _frec.arm("serving run loop")
        try:
            while True:
                # watchdog progress mark: a hung device fetch or a
                # scheduler livelock stops the beats and the flight
                # recorder dumps a diagnosable bundle (owner-token
                # scoped: another component's beats cannot mask us)
                _frec.beat(_wd_token)
                if inflight is not None:
                    # speculative successor first: device never idles
                    # while the host harvests, drains, and admits
                    nxt = spec_dispatch()
                    harvest(inflight)
                    done.extend(self._drain())
                    # admissions overlap nxt's on-device run — the
                    # gauge distinguishing overlapped from serialized
                    self._overlap_admission = nxt is not None
                    try:
                        self._admit()
                        after_admit()
                    finally:
                        self._overlap_admission = False
                    inflight = nxt
                    continue
                n_before = len(done)
                self._admit()
                done.extend(self._drain())
                progressed, inflight = idle_turn()
                if progressed:
                    continue
                if not self.queue:
                    break
                if (len(done) == n_before
                        and all(r is None for r in self.slot_req)):
                    # nothing running, nothing finished, head request
                    # still unadmittable — spinning never terminates.
                    # Dump a flight-recorder bundle first: the ring's
                    # recent scheduler turns + pool state are the
                    # post-mortem
                    rec = _frec.get_recorder()
                    if rec is not None:
                        _frec.record_event(
                            "serving_stall", queued=len(self.queue),
                            free_pages=len(self._free_pages))
                        try:
                            rec.dump("serving engine stalled: queued "
                                     "request cannot be admitted")
                        except OSError:
                            pass    # the diagnostic RuntimeError below
                                    # must not be replaced by a failed
                                    # bundle write
                    raise RuntimeError(
                        "serving engine stalled: queued request cannot "
                        "be admitted (page pool exhausted?)")
        finally:
            _frec.disarm(_wd_token)
            self._stats["run_seconds"] += time.perf_counter() - t_run0
            self._emit_gauges()
        return done

    # ---- unified batching step (ONE compiled program) --------------------

    def _worth_step(self):
        """Would a unified step advance anything? Prefilling slots
        always do; decode slots only while the host's ctx prediction
        leaves budget (an eos stop the host cannot see may still yield
        an empty step — counted in ``chunks_empty``)."""
        return bool(self._prefilling.any()
                    or np.any(self.active
                              & (self.limits > self._pred_ctx)))

    def _unified_static(self):
        """The ONE compiled batching-step program: a ragged mixed pass
        (prefill slots stream their next ``prefill_chunk`` prompt
        tokens, active decode slots ride their pending token as a
        length-1 sequence, idle slots are length 0 — one
        [num_slots, prefill_chunk] forward through
        ``ragged_paged_attention``) followed by ``decode_chunk - 1``
        in-program decode micro-steps. A slot whose prompt completes in
        the mixed pass samples its first token and starts decoding at
        micro-step 1 — prefill→decode transition never leaves the
        device, so no first-token echo machinery exists in this mode.
        The packed output carries every emitted token of the step plus
        the ctx/active mirrors in ONE int32 fetch."""
        if self._unified_fn is not None:
            return self._unified_fn
        from ..jit import to_static
        model = self.model
        greedy = self.greedy
        temperature = self.temperature
        C = self.prefill_chunk
        n_dec = self._n_decode

        def ustep(ids_t, nq_t, last_t, tgt_t, tok_t, ctx_t, act_t,
                  tbl_t, lim_t, eos_t, key_t, *pools):
            fwd = model.forward

            def fn(ids, nq, last, tgt, tok, ctx, act, tbl, lim,
                   eos_arr, key, *pool_leaves):
                b = tok.shape[0]
                # stale instant-eos guard (legacy chunk-entry contract)
                act = act & ((eos_arr < 0) | (tok != eos_arr))
                is_pre = nq > 0
                lengths = jnp.where(
                    is_pre, nq,
                    jnp.where(act, 1, 0)).astype(jnp.int32)
                # decode slots carry their device-resident pending
                # token in stream column 0
                ids_eff = ids.at[:, 0].set(
                    jnp.where(is_pre, ids[:, 0], tok))
                with no_grad():
                    logits, npools = fwd(
                        Tensor(ids_eff),
                        caches=[Tensor(a) for a in pool_leaves],
                        pos=Tensor(ctx[:, None]),
                        tables=(Tensor(tbl), Tensor(lengths)))
                lg = logits._data                      # [B, C, V]
                idx = jnp.clip(lengths - 1, 0, C - 1)
                last_lg = jnp.take_along_axis(
                    lg, idx[:, None, None], axis=1)[:, 0]
                last_lg = last_lg.astype(jnp.float32)
                if greedy:
                    sampled = jnp.argmax(last_lg, -1).astype(jnp.int32)
                else:
                    key, sub = jax.random.split(key)
                    sampled = jax.random.categorical(
                        sub, last_lg / temperature).astype(jnp.int32)
                # a next-token fires for completing prompts and for
                # advancing decode slots
                fire = (is_pre & last) | (act & ~is_pre)
                nxt = jnp.where(fire, sampled, tok)
                ctx1 = ctx + lengths
                hit_eos = (eos_arr >= 0) & (nxt == eos_arr)
                still_dec = act & ~is_pre & (ctx1 < lim) & ~hit_eos
                act_pre = is_pre & last & tgt & (ctx1 < lim) & ~hit_eos
                act1 = jnp.where(is_pre, act_pre, still_dec)
                out0 = jnp.where(fire, nxt, -1)

                def body(carry, _):
                    tok_c, ctx_c, act_c, key_c, leaves = carry
                    with no_grad():
                        lgs, ncaches = fwd(
                            Tensor(tok_c.reshape(b, 1)),
                            caches=[Tensor(a) for a in leaves],
                            pos=Tensor(ctx_c[:, None]),
                            tables=(Tensor(tbl), Tensor(act_c)))
                    lg_c = lgs[:, -1]._data.astype(jnp.float32)
                    if greedy:
                        nx = jnp.argmax(lg_c, -1).astype(jnp.int32)
                    else:
                        key_c, sub_c = jax.random.split(key_c)
                        nx = jax.random.categorical(
                            sub_c, lg_c / temperature).astype(jnp.int32)
                    ctx_n = ctx_c + act_c.astype(jnp.int32)
                    nx = jnp.where(act_c, nx, tok_c)
                    still = act_c & (ctx_n < lim) & \
                        ((eos_arr < 0) | (nx != eos_arr))
                    new_leaves = tuple(t._data for t in ncaches)
                    out_tok = jnp.where(act_c, nx, -1)
                    return (nx, ctx_n, still, key_c, new_leaves), \
                        (out_tok, act_c)

                carry0 = (nxt, ctx1, act1, key,
                          tuple(t._data for t in npools))
                if n_dec:
                    carry, (toks, emitted) = jax.lax.scan(
                        body, carry0, jnp.arange(n_dec))
                    tok_f, ctx_f, act_f, key_f, leaves_f = carry
                    toks_all = jnp.concatenate(
                        [out0[:, None], toks.T], axis=1)
                    emit_all = jnp.concatenate(
                        [fire[:, None], emitted.T], axis=1)
                else:
                    tok_f, ctx_f, act_f, key_f, leaves_f = carry0
                    toks_all = out0[:, None]
                    emit_all = fire[:, None]
                packed_out = jnp.concatenate(
                    [toks_all.astype(jnp.int32),
                     emit_all.astype(jnp.int32),
                     ctx_f[:, None].astype(jnp.int32),
                     act_f[:, None].astype(jnp.int32)], axis=1)
                return (packed_out, tok_f, ctx_f, act_f, key_f) \
                    + tuple(leaves_f)

            return _apply_multi(
                fn, [ids_t, nq_t, last_t, tgt_t, tok_t, ctx_t, act_t,
                     tbl_t, lim_t, eos_t, key_t] + list(pools),
                n_out=5 + len(pools))

        self._unified_fn = to_static(ustep)
        self._compiled.add(("unified", C, 1 + n_dec))
        return self._unified_fn

    def _dispatch_step(self):
        """Launch one unified step (async) and chain the device state.
        Returns an in-flight record for :meth:`_harvest_step` — the
        packed output is NOT fetched here, so a caller may overlap the
        fetch with the next step's on-device compute."""
        B, C = self.num_slots, self.prefill_chunk
        ids = np.zeros((B, C), np.int32)
        nq = np.zeros((B,), np.int32)
        last = np.zeros((B,), bool)
        tgt = np.zeros((B,), bool)
        n_pre = 0
        for slot in range(B):
            if not self._prefilling[slot] or n_pre >= self.admit_batch:
                continue
            req = self.slot_req[slot]
            off = int(self._prefill_off[slot])
            v = min(C, len(req.prompt) - off)
            ids[slot, :v] = req.prompt[off:off + v]
            nq[slot] = v
            last[slot] = off + v == len(req.prompt)
            tgt[slot] = self._act_target[slot]
            n_pre += 1
        fn = self._unified_static()
        self._seq += 1
        n_steps = 1 + self._n_decode
        # a slot advances this step if it decodes with budget left OR
        # streams prompt tokens (a completing prompt decodes the
        # in-program tail too, so its tokens must be credited here)
        n_active = int(np.sum((self.active
                               & (self.limits > self._pred_ctx))
                              | (nq > 0)))
        _t_obs = time.perf_counter()
        self._stats.inc("chunks")
        self._stats.inc("unified_steps")
        self._stats.inc("chunk_slot_steps", B * n_steps)
        if n_pre:
            self._stats.inc("prefill_waves")
        self._stats.inc("active_slot_steps", n_active * n_steps)
        from ..profiler.trace import get_tracer
        _tr = get_tracer()
        if _tr.enabled:
            _tr.counter("serving/active_slots", n_active,
                        queued=len(self.queue), chunk_len=n_steps,
                        prefilling=n_pre)
        _frec.record_event("sched_turn", seq=self._seq, mode="unified",
                           active=n_active, queued=len(self.queue),
                           prefilling=n_pre, chunk_len=n_steps)
        self._obs_s += time.perf_counter() - _t_obs
        res = fn(Tensor(jnp.asarray(ids)), Tensor(jnp.asarray(nq)),
                 Tensor(jnp.asarray(last)), Tensor(jnp.asarray(tgt)),
                 Tensor(self._dev_tok), Tensor(self._dev_ctx),
                 Tensor(self._dev_act), Tensor(self._dev_tbl),
                 Tensor(self._dev_lim), Tensor(self._dev_eos),
                 Tensor(self._key), *self.pools)
        packed, tok_f, ctx_f, act_f, key_f = res[:5]
        self.pools = list(res[5:])
        self._dev_tok = tok_f._data
        self._dev_ctx = ctx_f._data
        self._dev_act = act_f._data
        self._key = key_f._data
        # host bookkeeping: prompt-stream progress is exact; decode
        # activity is a prediction refined by the harvested mirrors
        emits = np.zeros((B,), bool)
        for slot in range(B):
            if nq[slot] > 0:
                self._prefill_off[slot] += nq[slot]
                if last[slot]:
                    req = self.slot_req[slot]
                    tl = len(req.prompt)
                    req.t_prefill_done = time.perf_counter()
                    self._prefilling[slot] = False
                    self.ctx[slot] = tl
                    # the first token + in-program decode tail land in
                    # THIS step; mirrors from any EARLIER in-flight
                    # step must not clobber the activation
                    self.active[slot] = bool(tgt[slot])
                    self._act_since[slot] = self._seq
                    self._pred_ctx[slot] = min(
                        int(self.limits[slot]), tl + self._n_decode)
                    emits[slot] = True
            elif self.active[slot] \
                    and self.limits[slot] > self._pred_ctx[slot]:
                self._pred_ctx[slot] = min(
                    int(self.limits[slot]),
                    int(self._pred_ctx[slot]) + n_steps)
                emits[slot] = True
        self._emits_inflight += emits.astype(np.int32)
        return (packed, list(self.slot_req), emits, n_steps, self._seq)

    def _harvest_step(self, rec):
        """Fetch one in-flight unified step's packed output and apply
        it: append emitted tokens, refresh the ctx/active mirrors
        (unless the slot was re-admitted, or activated by a LATER
        dispatch, since this step went out)."""
        packed, snap_req, emits, n_steps, seq = rec
        arr = np.asarray(packed._data)            # the ONE fetch
        toks_np = arr[:, :n_steps]
        emitted_np = arr[:, n_steps:2 * n_steps].astype(bool)
        ctx_m = arr[:, 2 * n_steps].astype(np.int32)
        act_m = arr[:, 2 * n_steps + 1].astype(bool)
        t_now = time.perf_counter()
        appended = 0
        for slot in range(self.num_slots):
            req = snap_req[slot]
            if req is not self.slot_req[slot]:
                continue      # slot re-admitted since this dispatch
            if emits[slot]:
                self._emits_inflight[slot] -= 1
            if self._act_since[slot] <= seq:
                self.ctx[slot] = ctx_m[slot]
                self.active[slot] = act_m[slot]
                self._pred_ctx[slot] = max(int(self._pred_ctx[slot]),
                                           int(ctx_m[slot]))
            if req is None or req.finished:
                continue
            for j in range(n_steps):
                if emitted_np[slot, j]:
                    if not req.tokens:
                        req.t_first = t_now
                    req.tokens.append(int(toks_np[slot, j]))
                    appended += 1
        _t_obs = time.perf_counter()
        self._stats.inc("tokens_emitted", appended)
        if appended == 0:
            self._stats.inc("chunks_empty")
        self._obs_s += time.perf_counter() - _t_obs

    def gauges(self) -> dict:
        """Serving observability surface (profiler subsystem):

        - ``slot_occupancy``: emitted tokens / dispatched slot-steps —
          the fraction of compiled slot-steps that produced a token.
        - ``active_occupancy``: slots active at dispatch / all slots —
          the drain/re-admit idle share specifically.
        - ``prefill_overlap_frac``: admissions made while a decode chunk
          was in flight (prefill waves then overlap its on-device run).
        - ``tokens_per_s``: emitted tokens / wall seconds inside run().
        - ``ttft_ms_p50/p99``: request-arrival → first-token-on-host
          percentiles (completed requests).
        - ``itl_ms_p50/p99``: smoothed inter-token latency percentiles —
          (t_done - t_first) / (tokens - 1) per request with ≥2 tokens.
        - ``compiled_programs``: distinct compiled signatures this
          engine built — steady-state 1 in unified mode (the single
          batching-step program); 1 prefill + the decode-chunk-length
          ladder in legacy mode. The compile-budget CI gate asserts on
          this.
        - ``chunks_empty``: harvested programs that delivered no
          tokens (unpredictable eos stops; structurally-wasted drain
          wave dispatches are eliminated).
        - ``prefill_waves``: programs that carried prompt tokens (in
          unified mode, unified steps with ≥1 prefilling slot).
        - ``unified_steps``: unified batching-step programs dispatched
          (0 in legacy mode).
        """
        s = self._stats.as_dict()
        steps = s["chunk_slot_steps"]
        return {
            "slot_occupancy": s["tokens_emitted"] / steps if steps
            else 0.0,
            "active_occupancy": s["active_slot_steps"] / steps if steps
            else 0.0,
            "prefill_overlap_frac": (s["prefills_overlapped"]
                                     / s["prefills"]) if s["prefills"]
            else 0.0,
            "tokens_per_s": (s["tokens_emitted"] / s["run_seconds"])
            if s["run_seconds"] else 0.0,
            "ttft_ms_p50": self._h_ttft.percentile(50),
            "ttft_ms_p99": self._h_ttft.percentile(99),
            "itl_ms_p50": self._h_itl.percentile(50),
            "itl_ms_p99": self._h_itl.percentile(99),
            "compiled_programs": len(self._compiled),
            "chunks_dispatched": s["chunks"],
            "chunks_empty": s["chunks_empty"],
            "prefill_waves": s["prefill_waves"],
            "unified_steps": s["unified_steps"],
            "tokens_emitted": s["tokens_emitted"],
            "prefills": s["prefills"],
            "requests_completed": s["requests_completed"],
            "obs_overhead_frac": (self._obs_s / s["run_seconds"])
            if s["run_seconds"] else 0.0,
        }

    def reset_gauges(self):
        """Zero the gauge counters (e.g. after a warmup run whose lazy
        compiles would otherwise pollute tokens_per_s). The compiled-
        signature set is NOT cleared — compiled programs persist on the
        engine, so the compile-budget counter stays truthful."""
        for k in self._stats:
            self._stats[k] = 0.0 if k == "run_seconds" else 0
        self._h_ttft.reset()
        self._h_itl.reset()
        self._obs_s = 0.0

    def _emit_gauges(self):
        _t_obs = time.perf_counter()
        s = self._stats.as_dict()
        self._g_overhead.set(
            (self._obs_s / s["run_seconds"]) if s["run_seconds"]
            else 0.0)
        from ..profiler.trace import get_tracer
        tr = get_tracer()
        if tr.enabled:
            for name, val in self.gauges().items():
                tr.counter(f"serving/{name}",
                           round(val, 6) if isinstance(val, float)
                           else val)
        self._obs_s += time.perf_counter() - _t_obs

    # ---- admission / chunked batched prefill -----------------------------

    def _alloc_pages(self, n):
        if len(self._free_pages) < n:
            return None
        return [self._free_pages.popleft() for _ in range(n)]

    def _admit(self):
        """Move queued requests into free slots: allocate pages, stage
        per-slot state, and mark the slot PREFILLING — the prompt itself
        streams through the batched prefill-chunk program in
        :meth:`_pump_prefill`."""
        for slot in range(self.num_slots):
            if not self.queue:
                return
            if self.active[slot] or self.slot_req[slot] is not None:
                continue
            req = self.queue[0]
            tl = len(req.prompt)
            need = -(-(tl + req.max_new_tokens) // self.page_size)
            pages = self._alloc_pages(need)
            if pages is None:
                return        # pool exhausted; retry after a drain
            self.queue.popleft()
            self.slot_pages[slot] = pages
            row = np.zeros((self.pages_per_slot,), np.int32)
            row[:len(pages)] = pages
            self.tables[slot] = row
            self._dev_tbl = self._dev_tbl.at[slot].set(jnp.asarray(row))
            req.t_admit = time.perf_counter()
            _t_obs = req.t_admit
            if self._trace_every:
                req.traced = req.request_id % self._trace_every == 0
            self._stats.inc("prefills")
            if self._overlap_admission:
                self._stats.inc("prefills_overlapped")
            from ..profiler.trace import get_tracer
            _tr = get_tracer()
            if _tr.enabled:
                _tr.instant("serving/prefill", slot=slot, prompt_len=tl,
                            chunk=self.prefill_chunk,
                            overlapped=self._overlap_admission)
            _frec.record_event("admit", slot=slot,
                               req=req.request_id, prompt_len=tl,
                               queued=len(self.queue))
            self._obs_s += time.perf_counter() - _t_obs
            self.slot_req[slot] = req
            self._prefilling[slot] = True
            self._prefill_off[slot] = 0
            self._emits_inflight[slot] = 0
            self._act_target[slot] = req.max_new_tokens > 1
            self.ctx[slot] = 0
            self._pred_ctx[slot] = 0
            self._dev_ctx = self._dev_ctx.at[slot].set(0)
            self.slot_eos[slot] = -1 if req.eos_token_id is None \
                else int(req.eos_token_id)
            # ctx counts CACHE entries; one generated token is always
            # pending outside the cache, so the n-th token lands when
            # ctx hits tl + n - 1 (not tl + n)
            self.limits[slot] = tl + req.max_new_tokens - 1
            self._dev_lim = self._dev_lim.at[slot].set(
                int(self.limits[slot]))
            self._dev_eos = self._dev_eos.at[slot].set(
                int(self.slot_eos[slot]))

    def _prefill_static(self):
        """The ONE compiled prefill signature: every wave — any mix of
        prompt lengths, any number of admitted prompts up to
        ``admit_batch`` — runs through this [num_slots, prefill_chunk]
        program. Writes pages incrementally, attends causally over the
        paged history, and samples the first token for slots whose
        prompt ends inside the chunk (it stays device-resident; the next
        decode chunk echoes it through the packed fetch)."""
        if self._prefill_fn is not None:
            return self._prefill_fn
        from ..jit import to_static
        model = self.model
        greedy = self.greedy
        temperature = self.temperature
        C = self.prefill_chunk

        def prefill(ids_t, pstart_t, valid_t, last_t, tgt_t, tok_t,
                    ctx_t, act_t, tbl_t, key_t, *pools):

            def fn(ids, pstart, valid, last, tgt, tok, ctx, act, tbl,
                   key, *pool_leaves):
                with no_grad():
                    logits, npools = model(
                        Tensor(ids),
                        caches=[Tensor(a) for a in pool_leaves],
                        pos=Tensor(pstart[:, None]),
                        tables=(Tensor(tbl), Tensor(valid)))
                lg = logits._data                        # [B, C, V]
                idx = jnp.clip(valid - 1, 0, C - 1)
                last_lg = jnp.take_along_axis(
                    lg, idx[:, None, None], axis=1)[:, 0]
                last_lg = last_lg.astype(jnp.float32)    # [B, V]
                if greedy:
                    sampled = jnp.argmax(last_lg, -1).astype(jnp.int32)
                else:
                    key, sub = jax.random.split(key)
                    sampled = jax.random.categorical(
                        sub, last_lg / temperature).astype(jnp.int32)
                fire = last & (valid > 0)
                tok2 = jnp.where(fire, sampled, tok)
                ctx2 = ctx + valid
                act2 = jnp.where(fire, tgt, act)
                return (tok2, ctx2, act2, key) + tuple(
                    t._data for t in npools)

            return _apply_multi(
                fn, [ids_t, pstart_t, valid_t, last_t, tgt_t, tok_t,
                     ctx_t, act_t, tbl_t, key_t] + list(pools),
                n_out=4 + len(pools))

        self._prefill_fn = to_static(prefill)
        self._compiled.add(("prefill", C))
        return self._prefill_fn

    def _pump_prefill(self, max_waves=None):
        """Dispatch batched prefill-chunk programs until every
        prefilling slot has streamed its whole prompt (or ``max_waves``
        waves were dispatched — the interleaving throttle). Entirely
        async: no host fetch; completion is host-predicted (prompt
        lengths are known)."""
        B, C = self.num_slots, self.prefill_chunk
        waves = 0
        while self._prefilling.any():
            if max_waves is not None and waves >= max_waves:
                return
            ids = np.zeros((B, C), np.int32)
            pstart = np.zeros((B,), np.int32)
            valid = np.zeros((B,), np.int32)
            last = np.zeros((B,), bool)
            tgt = np.zeros((B,), bool)
            batched = []
            for slot in range(B):
                if not self._prefilling[slot]:
                    continue
                if len(batched) >= self.admit_batch:
                    continue      # next wave picks it up
                req = self.slot_req[slot]
                off = int(self._prefill_off[slot])
                v = min(C, len(req.prompt) - off)
                ids[slot, :v] = req.prompt[off:off + v]
                pstart[slot] = off
                valid[slot] = v
                last[slot] = off + v == len(req.prompt)
                tgt[slot] = self._act_target[slot]
                batched.append(slot)
            fn = self._prefill_static()
            self._seq += 1
            self._stats["prefill_waves"] += 1
            res = fn(Tensor(jnp.asarray(ids)), Tensor(jnp.asarray(pstart)),
                     Tensor(jnp.asarray(valid)), Tensor(jnp.asarray(last)),
                     Tensor(jnp.asarray(tgt)), Tensor(self._dev_tok),
                     Tensor(self._dev_ctx), Tensor(self._dev_act),
                     Tensor(self._dev_tbl), Tensor(self._key),
                     *self.pools)
            tok2, ctx2, act2, key2 = res[:4]
            self.pools = list(res[4:])
            self._dev_tok = tok2._data
            self._dev_ctx = ctx2._data
            self._dev_act = act2._data
            self._key = key2._data
            for slot in batched:
                self._prefill_off[slot] += valid[slot]
                if not last[slot]:
                    continue
                # final wave for this prompt: host-side activation —
                # the sampled first token stays on device and is echoed
                # through the next decode chunk's packed fetch (or the
                # drain-time fetch for one-shot tail requests)
                req = self.slot_req[slot]
                tl = len(req.prompt)
                req.t_prefill_done = time.perf_counter()
                self._prefilling[slot] = False
                self.ctx[slot] = tl
                self._pred_ctx[slot] = tl
                self._pending_first[slot] = True
                self._act_since[slot] = self._seq
                # instant-eos (first token == stop token) is detected ON
                # DEVICE at the next chunk's entry; only the structural
                # one-token case is known host-side now
                self.active[slot] = bool(self._act_target[slot])
            waves += 1

    # ---- chunked decode --------------------------------------------------

    def _worth_dispatching(self):
        """Is there any slot a decode chunk could advance? With the
        host's ctx prediction this is exact for length-limited slots, so
        the structurally-wasted drain-wave dispatch never happens; an
        eos stop the host cannot see may still yield an empty chunk
        (counted in ``chunks_empty``)."""
        return bool(np.any(self.active & (self.limits > self._pred_ctx)))

    def _next_chunk_len(self):
        """Adaptive chunk length: clamp to the minimum predicted
        remaining budget across active slots so no slot oversteps its
        limit inside a chunk, quantized to a power-of-two ladder ≤
        ``decode_chunk`` to bound distinct compiled signatures."""
        if not self.adaptive_chunk:
            return self.decode_chunk
        rem = (self.limits - self._pred_ctx)[self.active
                                             & (self.limits
                                                > self._pred_ctx)]
        if rem.size == 0:
            return self.decode_chunk
        m = int(rem.min())
        if m >= self.decode_chunk:
            return self.decode_chunk
        return 1 << (m.bit_length() - 1)

    def _chunk_static(self, n_steps):
        fn = self._chunk_fns.get(n_steps)
        if fn is not None:
            return fn
        from ..jit import to_static
        model = self.model
        greedy = self.greedy
        temperature = self.temperature

        def chunk(tok_t, ctx_t, act_t, tbl_t, lim_t, eos_t, key_t,
                  *pools):
            fwd = model.forward

            def fn(tok, ctx, act, tbl, lim, eos_arr, key, *pool_leaves):
                b = tok.shape[0]
                # a freshly admitted slot whose prefill token already hit
                # its stop token must not decode (the host never saw the
                # token — instant-eos is detected here, on device)
                act = act & ((eos_arr < 0) | (tok != eos_arr))
                init_tok = tok

                def body(carry, _):
                    tok_c, ctx_c, act_c, key_c, leaves = carry
                    with no_grad():
                        logits, ncaches = fwd(
                            Tensor(tok_c.reshape(b, 1)),
                            caches=[Tensor(a) for a in leaves],
                            pos=Tensor(ctx_c[:, None]),
                            tables=(Tensor(tbl), Tensor(act_c)))
                    lg = logits[:, -1]._data.astype(jnp.float32)
                    if greedy:
                        nxt = jnp.argmax(lg, -1).astype(jnp.int32)
                    else:
                        key_c, sub = jax.random.split(key_c)
                        nxt = jax.random.categorical(
                            sub, lg / temperature).astype(jnp.int32)
                    ctx_n = ctx_c + act_c.astype(jnp.int32)
                    nxt = jnp.where(act_c, nxt, tok_c)
                    # per-slot eos (a traced [B] array, -1 = none): each
                    # request may carry its own stop token
                    still = act_c & (ctx_n < lim) & \
                        ((eos_arr < 0) | (nxt != eos_arr))
                    new_leaves = tuple(t._data for t in ncaches)
                    out_tok = jnp.where(act_c, nxt, -1)
                    return (nxt, ctx_n, still, key_c, new_leaves), \
                        (out_tok, act_c)

                carry0 = (tok, ctx, act, key, tuple(pool_leaves))
                carry, (toks, emitted) = jax.lax.scan(
                    body, carry0, jnp.arange(n_steps))
                tok_f, ctx_f, act_f, key_f, leaves_f = carry
                # ONE packed int32 fetch carries everything the host
                # scheduler needs: emitted tokens, emission mask, the
                # first-token echo for freshly admitted slots, and the
                # ctx/active mirrors
                packed_out = jnp.concatenate(
                    [toks.T.astype(jnp.int32),
                     emitted.T.astype(jnp.int32),
                     init_tok[:, None].astype(jnp.int32),
                     ctx_f[:, None].astype(jnp.int32),
                     act_f[:, None].astype(jnp.int32)], axis=1)
                return (packed_out, tok_f, ctx_f, act_f, key_f) \
                    + tuple(leaves_f)

            return _apply_multi(fn, [tok_t, ctx_t, act_t, tbl_t, lim_t,
                                     eos_t, key_t]
                                + list(pools), n_out=5 + len(pools))

        fn = to_static(chunk)
        self._chunk_fns[n_steps] = fn
        self._compiled.add(("chunk", n_steps))
        return fn

    def _dispatch_chunk(self):
        """Launch one chunk program (async) and chain the device state.
        Returns an in-flight record for :meth:`_harvest_chunk` — the
        packed output is NOT fetched here, so a caller may overlap the
        fetch with the next chunk's on-device compute."""
        n = self._next_chunk_len()
        fn = self._chunk_static(n)
        self._seq += 1
        # "active" for occupancy accounting = slots this chunk can
        # actually advance (host-active AND budget remaining); a slot
        # that exhausted its budget but has not drained yet is idle
        n_active = int(np.sum(self.active
                              & (self.limits > self._pred_ctx)))
        _t_obs = time.perf_counter()
        self._stats.inc("chunks")
        self._stats.inc("chunk_slot_steps", self.num_slots * n)
        self._stats.inc("active_slot_steps", n_active * n)
        from ..profiler.trace import get_tracer
        _tr = get_tracer()
        if _tr.enabled:
            _tr.counter("serving/active_slots", n_active,
                        queued=len(self.queue), chunk_len=n)
        _frec.record_event("sched_turn", seq=self._seq, mode="legacy",
                           active=n_active, queued=len(self.queue),
                           chunk_len=n)
        self._obs_s += time.perf_counter() - _t_obs
        res = fn(Tensor(self._dev_tok), Tensor(self._dev_ctx),
                 Tensor(self._dev_act), Tensor(self._dev_tbl),
                 Tensor(self._dev_lim), Tensor(self._dev_eos),
                 Tensor(self._key), *self.pools)
        packed, tok_f, ctx_f, act_f, key_f = res[:5]
        self.pools = list(res[5:])
        self._dev_tok = tok_f._data
        self._dev_ctx = ctx_f._data
        self._dev_act = act_f._data
        self._key = key_f._data
        self._pred_ctx = np.where(
            self.active,
            np.minimum(self.limits, self._pred_ctx + n),
            self._pred_ctx).astype(np.int32)
        # snapshot the slot->request mapping, the pending-first mask and
        # the dispatch seq: by harvest time a drained slot may have been
        # re-admitted (or a prefilling slot activated) — stale views
        # must not be applied
        rec = (packed, list(self.slot_req), self._pending_first.copy(),
               n, self._seq)
        self._echo_inflight |= self._pending_first
        self._pending_first[:] = False
        return rec

    def _harvest_chunk(self, rec):
        """Fetch one in-flight chunk's packed output and apply it."""
        packed, snap_req, pending, n, seq = rec
        arr = np.asarray(packed._data)            # the ONE fetch
        toks_np = arr[:, :n]
        emitted_np = arr[:, n:2 * n].astype(bool)
        init_tok = arr[:, 2 * n]
        ctx_m = arr[:, 2 * n + 1].astype(np.int32)
        act_m = arr[:, 2 * n + 2].astype(bool)
        t_now = time.perf_counter()
        appended = 0
        for slot in range(self.num_slots):
            if pending[slot]:
                # this harvest delivers the slot's first-token echo;
                # _drain may finish the slot again from here on
                self._echo_inflight[slot] = False
            req = snap_req[slot]
            if req is not self.slot_req[slot]:
                continue      # slot re-admitted since this dispatch
            if self._act_since[slot] <= seq:
                # the chunk's view of this slot is current (it was not
                # re-activated by a prefill wave after this dispatch)
                self.ctx[slot] = ctx_m[slot]
                self.active[slot] = act_m[slot]
            if req is None:
                continue
            if pending[slot]:
                if not req.tokens:
                    req.t_first = t_now
                req.tokens.append(int(init_tok[slot]))
                appended += 1
            if req.finished:
                continue
            for j in range(n):
                if emitted_np[slot, j]:
                    if not req.tokens:
                        req.t_first = t_now
                    req.tokens.append(int(toks_np[slot, j]))
                    appended += 1
        _t_obs = time.perf_counter()
        self._stats.inc("tokens_emitted", appended)
        if appended == 0:
            self._stats.inc("chunks_empty")
        self._obs_s += time.perf_counter() - _t_obs

    def _decode_chunk(self):
        self._harvest_chunk(self._dispatch_chunk())

    # ---- completion ------------------------------------------------------

    def _record_latency(self, req):
        """Book a finished request's latency into the bounded
        reservoirs and, for sampled requests, reconstruct its
        lifecycle spans into the chrome trace (queued → admitted →
        prefill → first-token → decode → finished) from the stamps
        taken on the hot path. Counted in the ``obs_overhead_frac``
        self-measurement window (the observes and the trace
        reconstruction ARE instrumentation cost)."""
        _t_obs = time.perf_counter()
        if req.t_first:
            self._h_ttft.observe((req.t_first - req.t_arrive) * 1e3)
            if len(req.tokens) > 1:
                self._h_itl.observe(
                    (req.t_done - req.t_first) * 1e3
                    / (len(req.tokens) - 1))
        if req.traced:
            self._emit_request_trace(req)
        self._obs_s += time.perf_counter() - _t_obs

    def _emit_request_trace(self, req):
        from ..profiler.trace import get_tracer
        tr = get_tracer()
        if not tr.enabled:
            return
        rid = int(req.request_id)
        # each traced request gets its own track (tid) so Perfetto
        # shows the lifecycle as one stacked lane per request
        admit = req.t_admit or req.t_arrive
        tr.complete("req/queued", req.t_arrive, admit,
                    cat="serving_req", tid=rid, request_id=rid)
        pre_end = req.t_prefill_done or req.t_first or admit
        tr.complete("req/prefill", admit, pre_end, cat="serving_req",
                    tid=rid, prompt_len=int(len(req.prompt)))
        if req.t_first:
            tr.complete("req/first_token_wait", pre_end, req.t_first,
                        cat="serving_req", tid=rid)
            tr.complete("req/decode", req.t_first, req.t_done,
                        cat="serving_req", tid=rid,
                        tokens=len(req.tokens))
        tr.instant("req/finished", cat="serving_req",
                   request_id=rid, reason=req.finish_reason,
                   tokens=len(req.tokens))

    def _drain(self):
        done = []
        for slot in range(self.num_slots):
            req = self.slot_req[slot]
            if req is None:
                continue
            if self._prefilling[slot]:
                # prompt still streaming through prefill waves — the
                # slot is inactive but very much occupied
                continue
            if self._echo_inflight[slot] or self._emits_inflight[slot]:
                # tokens for this slot ride a dispatched-but-
                # unharvested program: finishing now would lose them
                # (defer one loop)
                continue
            if not self.active[slot]:
                if self._pending_first[slot]:
                    # finished without any chunk running after prefill
                    # completion (one-token request at the tail of the
                    # workload): the first token never got echoed —
                    # fetch it now
                    req.t_first = time.perf_counter()
                    req.tokens.append(int(np.asarray(
                        self._dev_tok[slot])))
                    self._stats.inc("tokens_emitted")
                    self._pending_first[slot] = False
                if not req.finished:
                    req.finished = True
                    req.t_done = time.perf_counter()
                    eos = req.eos_token_id
                    req.finish_reason = "eos" if (
                        eos is not None and req.tokens
                        and req.tokens[-1] == eos) else "length"
                    self._record_latency(req)
                self._free_pages.extend(self.slot_pages[slot])
                self.slot_pages[slot] = []
                self.slot_req[slot] = None
                self.tables[slot] = 0
                self.ctx[slot] = 0
                self._pred_ctx[slot] = 0
                self.limits[slot] = 0
                self.slot_eos[slot] = -1
                self._prefill_off[slot] = 0
                self._act_target[slot] = False
                self.completed.append(req)
                _t_obs = time.perf_counter()
                self._stats.inc("requests_completed")
                _frec.record_event("finish", req=req.request_id,
                                   reason=req.finish_reason,
                                   tokens=len(req.tokens))
                self._obs_s += time.perf_counter() - _t_obs
                done.append(req)
        return done


def _apply_multi(fn, tensors, n_out):
    """apply() with a tuple return of n_out arrays."""
    from ..framework.core import apply
    return apply(fn, *tensors, n_outputs=n_out, differentiable=False,
                 name="serving_engine")


# -- tunable surface ---------------------------------------------------------
# The engine's chunk ladder is a tunable surface like the kernel tiles,
# but its trial needs a whole engine + workload, so there is no
# standalone builder: `bench.py --autotune`'s cb section is the sweep
# vehicle (it times candidate ladders on the real workload and commits
# the winner); a recorded winner then serves every ctor call that
# leaves the knobs as None. Candidate values are powers of two — the
# adaptive decode ladder and the compiled-signature budget both
# assume pow2.

def _register_serving_surface():
    from ..tuner.surface import TunableSurface, register_surface

    def _candidates(shape):
        slots = int(shape.get("slots", 4))
        max_len = int(shape.get("max_len", 512))
        out = []
        for dc in (8, 16, 32, 64):
            if dc > max_len:
                continue
            for pc in (32, 64, 128, 256):
                if pc > max_len:
                    continue
                for ab in sorted({1, max(slots // 2, 1), slots}):
                    out.append({"decode_chunk": dc, "prefill_chunk": pc,
                                "admit_batch": ab})
        return out

    def _is_valid(config, shape):
        slots = int(shape.get("slots", 4))
        max_len = int(shape.get("max_len", 512))
        return (1 <= config["decode_chunk"] <= max_len
                and 1 <= config["prefill_chunk"] <= max_len
                and 1 <= config["admit_batch"] <= slots)

    register_surface(TunableSurface(
        name="serving_chunks",
        params=("decode_chunk", "prefill_chunk", "admit_batch"),
        default={"decode_chunk": 16, "prefill_chunk": 128,
                 "admit_batch": 4},
        candidates=_candidates,
        is_valid=_is_valid,
        describe="ContinuousBatchingEngine ladder: decode chunk length, "
                 "batched-prefill chunk, prompts admitted per prefill "
                 "wave. Shape key: slots/max_len/page."))


_register_serving_surface()
